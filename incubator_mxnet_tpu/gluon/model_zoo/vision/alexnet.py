"""AlexNet for the TPU model zoo.

Layer constants follow Krizhevsky et al. (the one-tower variant the MXNet
zoo ships).  API and checkpoint-key parity with the reference zoo (ref:
python/mxnet/gluon/model_zoo/vision/alexnet.py) is asserted by
``tests/test_model_zoo_rewrite.py``.  The net is stamped out from two
spec tables instead of a hand-unrolled ``add`` ladder.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, Dense, Dropout, MaxPool2D,
                   Flatten)

__all__ = ["AlexNet", "alexnet"]

# (width, kernel, stride, pad, max-pool after?)
_STEM = [(64, 11, 4, 2, True),
         (192, 5, 1, 2, True),
         (384, 3, 1, 1, False),
         (256, 3, 1, 1, False),
         (256, 3, 1, 1, True)]
_HEAD_WIDTH, _HEAD_DROP = 4096, 0.5


class AlexNet(HybridBlock):
    """Five-conv stem driven by ``_STEM``, two dropout-regularised Dense
    layers, and a linear classifier."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            feats = HybridSequential(prefix="")
            for width, kernel, stride, pad, pool in _STEM:
                feats.add(Conv2D(width, kernel_size=kernel, strides=stride,
                                 padding=pad, activation="relu"))
                if pool:
                    feats.add(MaxPool2D(pool_size=3, strides=2))
            feats.add(Flatten())
            for _ in range(2):
                feats.add(Dense(_HEAD_WIDTH, activation="relu"))
                feats.add(Dropout(_HEAD_DROP))
            self.features = feats
            self.output = Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, ctx=None, root=None, **kwargs):
    """Build AlexNet; optionally load zoo weights."""
    net = AlexNet(**kwargs)
    if pretrained:
        from ..model_store import get_model_file
        net.load_params(get_model_file("alexnet", root=root), ctx=ctx)
    return net
