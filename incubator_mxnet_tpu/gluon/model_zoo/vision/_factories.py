"""Shared helper for stamping spec-driven model factories into a module."""
from __future__ import annotations


def stamp_factory(module_globals, name, doc, builder, *args, **forced_kwargs):
    """Define ``module_globals[name]`` as a factory calling ``builder``.

    ``args`` are bound positionally (e.g. version/depth picked from a spec
    table); ``forced_kwargs`` override anything the caller passes, matching
    the historical behaviour of the ``_bn`` variants.
    """
    def ctor(**kwargs):
        kwargs.update(forced_kwargs)
        return builder(*args, **kwargs)
    ctor.__name__ = name
    ctor.__qualname__ = name
    ctor.__module__ = module_globals.get("__name__", __name__)
    ctor.__doc__ = doc
    module_globals[name] = ctor
    return ctor
