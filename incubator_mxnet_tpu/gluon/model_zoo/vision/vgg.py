"""VGG 11/13/16/19 (± batch-norm) for the TPU model zoo.

Stage layout follows Simonyan & Zisserman (1409.1556, configs A/B/D/E).
API and checkpoint-key parity with the reference zoo (ref:
python/mxnet/gluon/model_zoo/vision/vgg.py) is asserted by
``tests/test_model_zoo_rewrite.py``.  The whole family — features,
classifier head, and the eight factory functions — is stamped out from
``vgg_spec`` by loops rather than per-depth classes.
"""
from __future__ import annotations

from ...block import HybridBlock
from ...nn import (HybridSequential, Conv2D, Dense, Dropout, MaxPool2D,
                   BatchNorm, Activation)
from .... import initializer

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn",
           "vgg16_bn", "vgg19_bn", "get_vgg"]

# depth -> (conv repeats per stage, stage widths)
vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}

class VGG(HybridBlock):
    """Plain conv stack: per stage, ``reps`` 3×3 convs then a 2× max-pool;
    two dropout-regularised 4096-wide Dense layers feed the classifier."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            feats = HybridSequential(prefix="")
            for reps, width in zip(layers, filters):
                for _ in range(reps):
                    feats.add(Conv2D(
                        width, kernel_size=3, padding=1,
                        weight_initializer=initializer.Xavier(
                            rnd_type="gaussian", factor_type="out",
                            magnitude=2),
                        bias_initializer="zeros"))
                    if batch_norm:
                        feats.add(BatchNorm())
                    feats.add(Activation("relu"))
                feats.add(MaxPool2D(strides=2))
            for _ in range(2):
                feats.add(Dense(4096, activation="relu",
                                weight_initializer="normal",
                                bias_initializer="zeros"))
                feats.add(Dropout(rate=0.5))
            self.features = feats
            self.output = Dense(classes, weight_initializer="normal",
                                bias_initializer="zeros")

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, pretrained=False, ctx=None, root=None, **kwargs):
    """Build a VGG by depth; optionally load zoo weights."""
    layers, filters = vgg_spec[num_layers]
    net = VGG(layers, filters, **kwargs)
    if pretrained:
        from ..model_store import get_model_file
        suffix = "_bn" if kwargs.get("batch_norm") else ""
        net.load_params(get_model_file("vgg%d%s" % (num_layers, suffix),
                                       root=root), ctx=ctx)
    return net


from ._factories import stamp_factory  # noqa: E402

for _depth in sorted(vgg_spec):
    stamp_factory(globals(), "vgg%d" % _depth,
                  "VGG-%d from vgg_spec." % _depth, get_vgg, _depth)
    stamp_factory(globals(), "vgg%d_bn" % _depth,
                  "VGG-%d with batch normalisation." % _depth,
                  get_vgg, _depth, batch_norm=True)
del _depth
