"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py, 238 LoC).

Applies an Optimizer to a set of Parameters. When a KVStore is attached the
gradient path mirrors the reference (trainer.py:156 _update → kvstore
push/pull or update_on_kvstore); on a device mesh the same step lowers to
psum-over-ICI via the parallel package instead of Comm/NCCL reductions.

graftfuse (the bucketed step path): ``step`` no longer walks parameters
one at a time.  Dense float parameters are greedily packed — in index
order, per dtype — into flat buckets of ~``GRAFT_BUCKET_BYTES`` (default
4 MiB); each bucket's gradients are concatenated into ONE buffer, reduced
across contexts as one elementwise tree-sum and across workers as one
collective (``KVStore.reduce_many`` → ``_cross_worker_reduce_many``), and
applied through ONE jitted multi-tensor optimizer program per
(optimizer-class, bucket signature) — ``optimizer.fused_bucket_update``.
The whole step stays on device (no ``_read()`` round trips between reduce
and update) and is bit-identical to the per-param path (the fused program
runs the same registered op formulas element-for-element).  Per-param
fallbacks: ``update_on_kvstore``, ``ignore_stale_grad``, gradient
compression, store-side updaters, sparse grads, and optimizers without a
fused kernel (anything but exact SGD/Adam).  One behavioral delta on the
fused path: reduced gradients are consumed directly by the update and are
NOT written back into ``param.list_grad()`` (``allreduce_grads()`` — the
grad-accumulation API — keeps exact per-key write-back semantics).
"""
from __future__ import annotations

import os

import numpy as np

from .. import engine as _engine
from .. import optimizer as opt
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_DEFAULT_BUCKET_BYTES = 4 << 20      # 4 MiB, the classic DDP bucket size


class _Bucket(object):
    """One (dtype, state-arity)-homogeneous gradient bucket of the fused
    step plan."""
    __slots__ = ("indices", "kind", "dtype", "nbytes")

    def __init__(self, indices, kind, dtype, nbytes):
        self.indices = tuple(indices)
        self.kind = kind
        self.dtype = dtype
        self.nbytes = nbytes


class Trainer(object):
    """ref: gluon/trainer.py class Trainer."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts, " \
                "but Parameter %s is initialized on %s while previous Parameters " \
                "are initialized on %s." % (param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Attach kvstore if requested (ref: trainer.py _init_kvstore)."""
        from .. import kvstore as kvs_mod
        arg_arrays = {param.name: param.data(self._contexts[0])
                      for param in self._params}
        kvstore, update_on_kvstore = kvs_mod.create_kvstore(
            self._kvstore, len(self._contexts), arg_arrays)
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if "dist" in kvstore.type:
                # dist_sync: the store is the in-graph allreduce of GRADS
                # (push then pull grads, update locally).  dist_async: the
                # store IS the weights — the host parameter server applies
                # every push with the server-side optimizer and pulls
                # return weights (kvstore_dist_server.h async mode)
                update_on_kvstore = "async" in kvstore.type
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore.init(list(range(len(self._params))),
                         [p.list_data()[0] for p in self._params])
            # pull EVERY param (frozen ones included): on dist stores the
            # init above broadcast rank 0's values, and a frozen layer left
            # at its local random init would make ranks diverge forever
            for i, param in enumerate(self._params):
                kvstore.pull(i, param.list_data(), priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore_obj = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore_obj = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate can be accessed.")
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        """ref: trainer.py set_learning_rate."""
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate is mutated.")
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size
        (ref: trainer.py:156 step).  Takes the bucketed fused path when
        the plan allows it; falls back to the (batched) per-param path
        otherwise — both produce bit-identical parameters."""
        # rescale BEFORE the kvstore handshake: update_on_kvstore ships a
        # pickled optimizer to the server exactly once, so the first
        # step's scaling must already be on it (reference limitation too:
        # later batch-size changes don't reach the server copy)
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        plan = None if ignore_stale_grad else self._fused_plan()
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import tracing as _ttracing
        # graftwatch step journal: one flight-recorder event per step
        # with kvstore/update phase latencies + device-memory highwater;
        # a crash or hang mid-step names the phase it stopped in
        with _blackbox.step_journal("trainer", batch_size=batch_size,
                                    fused=plan is not None):
            with _ttracing.phase_span("kvstore"):
                if plan is None:
                    self._allreduce_grads()
                else:
                    reduced = self._bucketed_allreduce(plan)
            with _ttracing.phase_span("update"):
                if plan is None:
                    self._update(ignore_stale_grad)
                else:
                    self._bucketed_update(plan, reduced)

    def allreduce_grads(self):
        """ref: trainer.py allreduce_grads (1.3+, for grad accumulation)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore_obj is None:
            return
        # one batched multi-key push/pull: a single fused dist collective
        # for the whole gradient set instead of one round per key (the
        # batching role of kvstore_dist.h's big-array sharding)
        keys = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not keys:
            return
        grads = [self._params[i].list_grad() for i in keys]
        self._kvstore_obj.push_many(keys, grads)
        if not self._update_on_kvstore:
            self._kvstore_obj.pull_many(keys, grads)

    def update(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py update (apply updates without reduce)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore_obj is not None and self._update_on_kvstore:
            keys = [i for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if keys:
                self._kvstore_obj.pull_many(
                    keys, [self._params[i].list_data() for i in keys])
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    # -- graftfuse: the bucketed step path ---------------------------------
    _bucket_bytes_override = None     # tests/benches force a target here

    def _bucket_target_bytes(self):
        if self._bucket_bytes_override is not None:
            return int(self._bucket_bytes_override)
        try:
            return int(os.environ.get("GRAFT_BUCKET_BYTES",
                                      str(_DEFAULT_BUCKET_BYTES)))
        except ValueError:
            return _DEFAULT_BUCKET_BYTES

    def _fused_plan(self):
        """The bucket plan for the current configuration, or None when
        step() must take the per-param path wholesale.  Cached against a
        signature of everything the plan depends on, so steady-state
        steps pay one tuple comparison."""
        target = self._bucket_target_bytes()
        kv = self._kvstore_obj
        if target <= 0 or self._update_on_kvstore \
                or (kv is not None and (kv._compressor is not None
                                        or kv._updater is not None)):
            return None
        optimizer = self._optimizer
        # per-param state arity rides in the signature AND the bucket
        # key: existing states keep the formula they were created with
        # (e.g. momentum flipped mid-run only affects states created
        # afterwards, exactly like the per-param path), so a fused
        # program must never mix arities
        states0 = self._updaters[0].states
        kinds, arities = [], []
        for i, p in enumerate(self._params):
            kind = opt.fused_bucket_kind(optimizer, p.dtype) \
                if p.grad_req != "null" else None
            kinds.append(kind)
            arities.append(None if kind is None else (
                opt.fused_state_arity(optimizer, kind, states0[i])
                if i in states0 else opt.fused_state_arity(optimizer, kind)))
        sig = (target, type(optimizer), bool(optimizer.multi_precision),
               getattr(optimizer, "momentum", None), tuple(arities),
               len(self._contexts), kv is not None,
               tuple((str(p.dtype), p.shape, p.grad_req, p._stype,
                      p._grad_stype) for p in self._params))
        cached = getattr(self, "_fused_plan_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        open_buckets = {}       # (dtype, arity) -> (indices, nbytes)
        buckets, leftover = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            kind = kinds[i]
            dense = p._stype == "default" and p._grad_stype == "default"
            known = p.shape is not None and int(np.prod(p.shape)) > 0
            if kind is None or not dense or not known:
                leftover.append(i)
                continue
            dt = np.dtype(p.dtype)
            bkey = (dt, arities[i])
            nbytes = int(np.prod(p.shape)) * dt.itemsize
            idxs, total = open_buckets.setdefault(bkey, ([], 0))
            idxs.append(i)
            total += nbytes
            if total >= target:
                buckets.append(_Bucket(idxs, kind, dt, total))
                open_buckets.pop(bkey)
            else:
                open_buckets[bkey] = (idxs, total)
        for (dt, _arity), (idxs, total) in open_buckets.items():
            buckets.append(_Bucket(idxs, opt.fused_bucket_kind(
                optimizer, dt), dt, total))
        plan = (buckets, leftover) if buckets else None
        self._fused_plan_cache = (sig, plan)
        if plan is not None:
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_buckets([b.nbytes for b in buckets],
                                      len(leftover))
        return plan

    def _bucketed_allreduce(self, plan):
        """Reduce every bucket's gradients with ONE concatenated buffer
        per bucket: contexts tree-sum elementwise (the same addition
        order as KVStore._reduce), workers allreduce through
        ``KVStore.reduce_many`` in one fused collective.  Returns
        {id(bucket): flat reduced NDArray}; empty when there is no store
        (the fused update then reads the per-param grads directly)."""
        from ..ndarray import NDArray
        buckets, leftover = plan
        kv = self._kvstore_obj
        if kv is not None and leftover:
            grads = [self._params[i].list_grad() for i in leftover]
            kv.push_many(leftover, grads)
            kv.pull_many(leftover, grads)
        if kv is None:
            return {}
        flats = []
        for b in buckets:
            per_ctx = [
                _engine.flatten_arrays(tuple(
                    self._params[i].list_grad()[j]._read()
                    for i in b.indices))
                for j in range(len(self._contexts))]
            acc = per_ctx[0]
            for f in per_ctx[1:]:
                acc = acc + f
            flats.append(NDArray(acc, ctx=self._contexts[0]))
        kv.reduce_many(flats)
        return {id(b): nd for b, nd in zip(buckets, flats)}

    def _bucketed_update(self, plan, reduced):
        """One fused multi-tensor optimizer dispatch per (bucket,
        context); leftover params take the per-param updater."""
        buckets, leftover = plan
        optimizer = self._optimizer
        n_ctx = len(self._contexts)
        for b in buckets:
            # bookkeeping ticks in the exact per-param order (param
            # outer, context inner) so update counts, schedulers and
            # Adam's bias correction see the same sequence
            lrs = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            wds = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            for pos, i in enumerate(b.indices):
                for j in range(n_ctx):
                    lr, wd = opt.fused_lr_wd(optimizer, i, b.kind)
                    lrs[j][pos] = lr
                    wds[j][pos] = wd
            flat = reduced.get(id(b))
            for j in range(n_ctx):
                weights = [self._params[i].list_data()[j]
                           for i in b.indices]
                grads = None if flat is not None else \
                    [self._params[i].list_grad()[j] for i in b.indices]
                opt.fused_bucket_update(optimizer, self._updaters[j],
                                        b.indices, weights, grads,
                                        lrs[j], wds[j], flat_grad=flat)
        for i in leftover:
            param = self._params[i]
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def save_states(self, fname):
        """ref: trainer.py:202 save_states."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                # dist_async: optimizer state lives on the parameter
                # server (same limitation as the reference's PS mode)
                raise ValueError(
                    "Cannot save trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            with open(fname, "wb") as fout:
                fout.write(self._kvstore_obj._updater.get_states(dump_optimizer=True))
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """ref: trainer.py:218 load_states."""
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                raise ValueError(
                    "Cannot load trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            self._kvstore_obj._updater.set_states(states)
            self._kvstore_obj._updater.optimizer.param_dict = {
                i: param for i, param in enumerate(self._params)}
            self._optimizer = self._kvstore_obj._updater.optimizer
        else:
            for updater in self._updaters:
                updater.set_states(states)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: param
                                      for i, param in enumerate(self._params)}
