"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py, 238 LoC).

Applies an Optimizer to a set of Parameters. When a KVStore is attached the
gradient path mirrors the reference (trainer.py:156 _update → kvstore
push/pull or update_on_kvstore); on a device mesh the same step lowers to
psum-over-ICI via the parallel package instead of Comm/NCCL reductions.

graftfuse (the bucketed step path): ``step`` no longer walks parameters
one at a time.  Dense float parameters are greedily packed — in index
order, per dtype — into flat buckets of ~``GRAFT_BUCKET_BYTES`` (default
4 MiB); each bucket's gradients are concatenated into ONE buffer, reduced
across contexts as one elementwise tree-sum and across workers as one
collective (``KVStore.reduce_many`` → ``_cross_worker_reduce_many``), and
applied through ONE jitted multi-tensor optimizer program per
(optimizer-class, bucket signature) — ``optimizer.fused_bucket_update``.
The whole step stays on device (no ``_read()`` round trips between reduce
and update) and is bit-identical to the per-param path (the fused program
runs the same registered op formulas element-for-element).  Per-param
fallbacks: ``update_on_kvstore``, ``ignore_stale_grad``, gradient
compression, store-side updaters, sparse grads, and optimizers without a
fused kernel (anything but exact SGD/Adam).  One behavioral delta on the
fused path: reduced gradients are consumed directly by the update and are
NOT written back into ``param.list_grad()`` (``allreduce_grads()`` — the
grad-accumulation API — keeps exact per-key write-back semantics).
"""
from __future__ import annotations

import os
import time
import weakref

import numpy as np

from .. import engine as _engine
from .. import optimizer as opt
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_DEFAULT_BUCKET_BYTES = 4 << 20      # 4 MiB, the classic DDP bucket size


class _Bucket(object):
    """One (dtype, state-arity)-homogeneous gradient bucket of the fused
    step plan."""
    __slots__ = ("indices", "kind", "dtype", "nbytes")

    def __init__(self, indices, kind, dtype, nbytes):
        self.indices = tuple(indices)
        self.kind = kind
        self.dtype = dtype
        self.nbytes = nbytes


class _BucketScheduler(object):
    """graftlap: issue each bucket's gradient allreduce DURING backward.

    Armed by ``Trainer.step`` with the current fused plan, the scheduler
    hangs a grad-ready hook on every eligible parameter's data arrays
    (autograd fires it the moment that parameter's gradient is final —
    see ``autograd._run_backward``).  When the last (param, context) pair
    of a bucket reports ready, the bucket's concatenated flat gradient is
    built with the EXACT serial-path math (``Trainer._bucket_flat``) and
    shipped through ``KVStore.reduce_many_async`` — an in-flight handle
    with its own flight-recorder bracket — while backward keeps producing
    earlier-layer gradients.  ``Trainer.step`` then only *waits* on the
    handles.  Because the hook order is the reverse-topological walk of a
    tape every rank shares (SPMD), the issue order of the collectives is
    identical on every worker: the lockstep contract holds.

    Safety rails (each one degrades to the serial PR-4 reduce, never to
    wrong values):

    * hooks fire only on a plain full backward — ``retain_graph``,
      ``create_graph`` and explicit-variables passes suppress them;
    * a hook under a NEW ``autograd.backward_pass_id()`` abandons every
      handle of the previous pass before scheduling restarts (a second
      backward overwrote the reduced grads);
    * only buckets whose params all have ``grad_req == "write"`` are
      eligible ("add" accumulation means grads are not final per pass);
    * at consume time every grad's ``_version`` must still match its
      issue-time stamp (gradient clipping or any other post-backward
      mutation invalidates the handle);
    * a scheduler exception marks it broken for the step instead of
      propagating into the user's backward.
    """

    __slots__ = ("_trainer_ref", "_armed", "_waiting", "_hooked",
                 "_buckets", "_pass_id", "_broken", "_plan", "_hook",
                 "issued_total", "taken_total", "__weakref__")

    def __init__(self, trainer):
        self._trainer_ref = weakref.ref(trainer)
        # ONE hook closure, created once (`self._on_ready` builds a fresh
        # bound method per attribute access, so ad-hoc accessors would
        # never pass disarm's identity check and hooks would leak), and
        # holding the scheduler WEAKLY: a bound method would pin the
        # scheduler — and through nothing else, the arrays its hooks sit
        # on — alive long after the Trainer is dropped, keeping the
        # autograd hook-source gate open forever.  With the weakref the
        # scheduler dies with its Trainer; orphaned hook attrs left on
        # param arrays degrade to a dead-ref no-op until overwritten.
        sched_ref = weakref.ref(self)

        def _hook(arr, _ref=sched_ref):
            sched = _ref()
            if sched is not None:
                sched._on_ready(arr)
        self._hook = _hook
        self._armed = False
        self._waiting = {}      # id(data NDArray) -> (bucket state, i, j)
        self._hooked = []       # data NDArrays carrying our hook
        self._buckets = {}      # id(bucket) -> state dict
        self._pass_id = None
        self._broken = False
        self._plan = None       # the armed plan, held STRONGLY: identity
        #                         (same cached tuple) means same plan, and
        #                         the ref pins it so a recycled id() can
        #                         never alias a new plan
        self.issued_total = 0   # buckets issued mid-backward (ever)
        self.taken_total = 0    # issued buckets actually consumed by step

    # -- arming -------------------------------------------------------------
    def arm(self, plan):
        """Install hooks for ``plan``'s eligible buckets (called at the
        end of every overlapped step, so the NEXT backward schedules).
        Steady state — same (cached) plan object, scheduler healthy —
        skips the reinstall: the next backward's first hook resets the
        pending sets via the pass-id rollover, so re-arming is O(1)."""
        if self._armed and not self._broken and self._plan is plan:
            self._abandon_all()
            for state in self._buckets.values():
                state["handle"] = None
                state["flat"] = None
            self._pass_id = None    # next hook rebuilds pending sets
            return
        self.disarm()
        trainer = self._trainer_ref()
        if trainer is None:
            return
        buckets, _leftover = plan
        for b in buckets:
            if any(trainer._params[i].grad_req != "write"
                   for i in b.indices):
                continue        # "add" accumulation: never final per pass
            state = {"bucket": b, "pending": set(), "handle": None,
                     "flat": None, "versions": None, "grads": []}
            for i in b.indices:
                grads = trainer._params[i].list_grad()
                for j, d in enumerate(trainer._params[i].list_data()):
                    state["pending"].add((i, j))
                    state["grads"].append(grads[j])
                    self._waiting[id(d)] = (state, i, j)
                    d._grad_ready_hook = self._hook
                    self._hooked.append(d)
            if state["pending"]:
                self._buckets[id(b)] = state
        self._armed = bool(self._buckets)
        if self._armed:
            from .. import autograd
            autograd.register_hook_source(self)
        self._plan = plan if self._armed else None
        self._pass_id = None
        self._broken = False

    def disarm(self):
        """Drop hooks and abandon anything still in flight."""
        for d in self._hooked:
            if getattr(d, "_grad_ready_hook", None) is self._hook:
                d._grad_ready_hook = None
        self._hooked = []
        self._waiting = {}
        self._abandon_all()
        self._buckets = {}
        self._armed = False
        self._plan = None
        from .. import autograd
        autograd.unregister_hook_source(self)

    def _abandon_all(self):
        for state in self._buckets.values():
            if state["handle"] is not None:
                state["handle"].abandon()
                state["handle"] = None

    # -- the hook (fires inside autograd._run_backward) ---------------------
    def _on_ready(self, arr):
        if not self._armed or self._broken:
            return
        if self._trainer_ref() is None:
            # the Trainer is gone but something still holds the scheduler
            # (a kept `t._scheduler` ref): clean up after ourselves
            self.disarm()
            return
        try:
            from .. import autograd
            pass_id = autograd.backward_pass_id()
            if pass_id != self._pass_id:
                # new backward pass: everything issued for the previous
                # one reduces grads that were just overwritten — discard
                # and start this pass clean
                n_ctx = self._ctx_count()
                self._abandon_all()
                for state in self._buckets.values():
                    state["pending"] = {(i, j)
                                        for i in state["bucket"].indices
                                        for j in range(n_ctx)}
                self._pass_id = pass_id
            entry = self._waiting.get(id(arr))
            if entry is None:
                return
            state, i, j = entry
            state["pending"].discard((i, j))
            if not state["pending"] and state["handle"] is None:
                self._issue(state)
        except Exception:
            self._broken = True
            self._abandon_all()
            raise               # _fire_ready_hook catches + logs; the
            #                     user's backward pass is unaffected

    def _ctx_count(self):
        trainer = self._trainer_ref()
        return len(trainer._contexts) if trainer is not None else 0

    def _issue(self, state):
        """All grads of one bucket are final: build the flat buffer and
        put its reduce on the wire, without joining (or flushing) any
        bulk segment the surrounding code has open."""
        trainer = self._trainer_ref()
        if trainer is None:
            return
        kv = trainer._kvstore_obj
        if kv is None:
            return
        b = state["bucket"]
        with _engine.offband():
            flat = trainer._bucket_flat(b)
            state["versions"] = [g._version for g in state["grads"]]
            state["flat"] = flat
            state["handle"] = kv.reduce_many_async(
                [flat], label="bucket[%s:%dp:%dB]" % (
                    np.dtype(b.dtype).name, len(b.indices), b.nbytes))
        self.issued_total += 1

    # -- consuming (Trainer.step) -------------------------------------------
    def take(self, plan):
        """Hand the step the buckets whose reduces are validly in flight:
        ``{id(bucket): (flat NDArray, ReduceHandle)}``.  Stale handles
        (grad versions moved since issue) are abandoned; everything is
        one-shot — the caller re-arms for the next step."""
        trainer = self._trainer_ref()
        out = {}
        if trainer is None or not self._armed or self._broken:
            self._abandon_all()
            return out
        buckets, _leftover = plan
        by_id = {id(b): b for b in buckets}
        for bid, state in self._buckets.items():
            handle = state["handle"]
            if handle is None:
                continue
            b = by_id.get(bid)
            if b is None:
                handle.abandon()        # plan changed under us
                continue
            if [g._version for g in state["grads"]] != state["versions"]:
                handle.abandon()        # stale grads: serial fallback
                continue
            out[bid] = (state["flat"], handle)
            state["handle"] = None      # consumed
        self.taken_total += len(out)
        return out


class Trainer(object):
    """ref: gluon/trainer.py class Trainer."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore
        self._scheduler = _BucketScheduler(self)

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts, " \
                "but Parameter %s is initialized on %s while previous Parameters " \
                "are initialized on %s." % (param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Attach kvstore if requested (ref: trainer.py _init_kvstore)."""
        from .. import kvstore as kvs_mod
        arg_arrays = {param.name: param.data(self._contexts[0])
                      for param in self._params}
        kvstore, update_on_kvstore = kvs_mod.create_kvstore(
            self._kvstore, len(self._contexts), arg_arrays)
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if "dist" in kvstore.type:
                # dist_sync: the store is the in-graph allreduce of GRADS
                # (push then pull grads, update locally).  dist_async: the
                # store IS the weights — the host parameter server applies
                # every push with the server-side optimizer and pulls
                # return weights (kvstore_dist_server.h async mode)
                update_on_kvstore = "async" in kvstore.type
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore.init(list(range(len(self._params))),
                         [p.list_data()[0] for p in self._params])
            # pull EVERY param (frozen ones included): on dist stores the
            # init above broadcast rank 0's values, and a frozen layer left
            # at its local random init would make ranks diverge forever
            for i, param in enumerate(self._params):
                kvstore.pull(i, param.list_data(), priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore_obj = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore_obj = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate can be accessed.")
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        """ref: trainer.py set_learning_rate."""
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate is mutated.")
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size
        (ref: trainer.py:156 step).  Takes the bucketed fused path when
        the plan allows it; falls back to the (batched) per-param path
        otherwise — both produce bit-identical parameters."""
        # rescale BEFORE the kvstore handshake: update_on_kvstore ships a
        # pickled optimizer to the server exactly once, so the first
        # step's scaling must already be on it (reference limitation too:
        # later batch-size changes don't reach the server copy)
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        plan = None if ignore_stale_grad else self._fused_plan()
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import tracing as _ttracing
        # graftwatch step journal: one flight-recorder event per step
        # with kvstore/update phase latencies + device-memory highwater;
        # a crash or hang mid-step names the phase it stopped in
        overlap = plan is not None and self._overlap_enabled() \
            and not self._update_on_kvstore and self._kvstore_obj is not None
        with _blackbox.step_journal("trainer", batch_size=batch_size,
                                    fused=plan is not None,
                                    overlapped=overlap):
            with _ttracing.phase_span("kvstore"):
                if plan is None:
                    self._scheduler.disarm()
                    self._allreduce_grads()
                else:
                    reduced = self._bucketed_allreduce(plan)
            with _ttracing.phase_span("update"):
                if plan is None:
                    self._update(ignore_stale_grad)
                else:
                    self._bucketed_update(plan, reduced)
        # graftlap: (re-)arm the grad-ready hooks so the NEXT backward
        # issues each bucket's reduce the moment its grads finalize;
        # first step after any config change runs serial (the plan must
        # exist before hooks know the buckets)
        if overlap:
            self._scheduler.arm(plan)
        elif self._scheduler._armed:
            self._scheduler.disarm()

    def allreduce_grads(self):
        """ref: trainer.py allreduce_grads (1.3+, for grad accumulation)."""
        if not self._kv_initialized:
            self._init_kvstore()
        # the accumulation API reduces INTO param.grad() with write-back
        # semantics; anything graftlap issued against the same grads is
        # unrelated to this call — drop it so no bracket stays open
        self._scheduler.disarm()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore_obj is None:
            return
        # one batched multi-key push/pull: a single fused dist collective
        # for the whole gradient set instead of one round per key (the
        # batching role of kvstore_dist.h's big-array sharding)
        keys = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not keys:
            return
        grads = [self._params[i].list_grad() for i in keys]
        self._kvstore_obj.push_many(keys, grads)
        if not self._update_on_kvstore:
            self._kvstore_obj.pull_many(keys, grads)

    def update(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py update (apply updates without reduce)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore_obj is not None and self._update_on_kvstore:
            keys = [i for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if keys:
                self._kvstore_obj.pull_many(
                    keys, [self._params[i].list_data() for i in keys])
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    # -- graftfuse: the bucketed step path ---------------------------------
    _bucket_bytes_override = None     # tests/benches force a target here
    _overlap_override = None          # tests/benches force overlap on/off

    def _bucket_target_bytes(self):
        if self._bucket_bytes_override is not None:
            return int(self._bucket_bytes_override)
        try:
            return int(os.environ.get("GRAFT_BUCKET_BYTES",
                                      str(_DEFAULT_BUCKET_BYTES)))
        except ValueError:
            return _DEFAULT_BUCKET_BYTES

    def _overlap_enabled(self):
        """GRAFT_OVERLAP (default on): overlap bucket reduces with the
        backward pass (graftlap).  Like GRAFT_BLACKBOX, multi-host jobs
        must set it IDENTICALLY on every rank — the issue order of the
        overlapped collectives is part of the lockstep contract."""
        if self._overlap_override is not None:
            return bool(self._overlap_override)
        return os.environ.get("GRAFT_OVERLAP", "1").strip().lower() \
            not in ("0", "false", "no", "off")

    def _fused_plan(self):
        """The bucket plan for the current configuration, or None when
        step() must take the per-param path wholesale.  Cached against a
        signature of everything the plan depends on, so steady-state
        steps pay one tuple comparison."""
        target = self._bucket_target_bytes()
        kv = self._kvstore_obj
        if target <= 0 or self._update_on_kvstore \
                or (kv is not None and (kv._compressor is not None
                                        or kv._updater is not None)):
            return None
        optimizer = self._optimizer
        # per-param state arity rides in the signature AND the bucket
        # key: existing states keep the formula they were created with
        # (e.g. momentum flipped mid-run only affects states created
        # afterwards, exactly like the per-param path), so a fused
        # program must never mix arities
        states0 = self._updaters[0].states
        kinds, arities = [], []
        for i, p in enumerate(self._params):
            kind = opt.fused_bucket_kind(optimizer, p.dtype) \
                if p.grad_req != "null" else None
            kinds.append(kind)
            arities.append(None if kind is None else (
                opt.fused_state_arity(optimizer, kind, states0[i])
                if i in states0 else opt.fused_state_arity(optimizer, kind)))
        sig = (target, type(optimizer), bool(optimizer.multi_precision),
               getattr(optimizer, "momentum", None), tuple(arities),
               len(self._contexts), kv is not None,
               tuple((str(p.dtype), p.shape, p.grad_req, p._stype,
                      p._grad_stype) for p in self._params))
        cached = getattr(self, "_fused_plan_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        open_buckets = {}       # (dtype, arity) -> (indices, nbytes)
        buckets, leftover = [], []
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            kind = kinds[i]
            dense = p._stype == "default" and p._grad_stype == "default"
            known = p.shape is not None and int(np.prod(p.shape)) > 0
            if kind is None or not dense or not known:
                leftover.append(i)
                continue
            dt = np.dtype(p.dtype)
            bkey = (dt, arities[i])
            nbytes = int(np.prod(p.shape)) * dt.itemsize
            idxs, total = open_buckets.setdefault(bkey, ([], 0))
            idxs.append(i)
            total += nbytes
            if total >= target:
                buckets.append(_Bucket(idxs, kind, dt, total))
                open_buckets.pop(bkey)
            else:
                open_buckets[bkey] = (idxs, total)
        for (dt, _arity), (idxs, total) in open_buckets.items():
            buckets.append(_Bucket(idxs, opt.fused_bucket_kind(
                optimizer, dt), dt, total))
        plan = (buckets, leftover) if buckets else None
        self._fused_plan_cache = (sig, plan)
        if plan is not None:
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_buckets([b.nbytes for b in buckets],
                                      len(leftover))
        return plan

    def _bucket_flat(self, b):
        """One bucket's concatenated local gradient: per-context flatten
        (one jitted dispatch each) + elementwise context tree-sum in
        context order — THE packing math, shared verbatim by the serial
        step path and the overlapped mid-backward issue so the two are
        bit-identical by construction."""
        from ..ndarray import NDArray
        per_ctx = [
            _engine.flatten_arrays(tuple(
                self._params[i].list_grad()[j]._read()
                for i in b.indices))
            for j in range(len(self._contexts))]
        acc = per_ctx[0]
        for f in per_ctx[1:]:
            acc = acc + f
        return NDArray(acc, ctx=self._contexts[0])

    def _bucketed_allreduce(self, plan):
        """Reduce every bucket's gradients with ONE concatenated buffer
        per bucket: contexts tree-sum elementwise (the same addition
        order as KVStore._reduce), workers allreduce through
        ``KVStore.reduce_many`` in one fused collective.  Returns
        {id(bucket): flat reduced NDArray}; empty when there is no store
        (the fused update then reads the per-param grads directly).

        graftlap: buckets whose reduce the scheduler already put on the
        wire mid-backward are only WAITED on here (same buffer, same
        reduction, earlier issue time); buckets that missed the overlap
        window — first step, stale grads, hook fallback — take the
        serial reduce exactly as before.  Wait order is plan order on
        every rank."""
        buckets, leftover = plan
        kv = self._kvstore_obj
        if kv is not None and leftover:
            grads = [self._params[i].list_grad() for i in leftover]
            kv.push_many(leftover, grads)
            kv.pull_many(leftover, grads)
        if kv is None:
            return {}
        overlap = self._overlap_enabled() and not self._update_on_kvstore
        issued = self._scheduler.take(plan) if overlap else {}
        serial = [b for b in buckets if id(b) not in issued]
        flats = {id(b): self._bucket_flat(b) for b in serial}
        if serial:
            kv.reduce_many([flats[id(b)] for b in serial])
        reduced, exposed_s, inflight_s = {}, 0.0, 0.0
        for b in buckets:
            entry = issued.get(id(b))
            if entry is None:
                reduced[id(b)] = flats[id(b)]
                continue
            flat, handle = entry
            t0 = time.perf_counter()
            handle.wait()
            t1 = time.perf_counter()
            exposed_s += t1 - t0
            inflight_s += t1 - handle.issued_at
            reduced[id(b)] = flat
        if overlap:
            if issued:
                # a fully-overlapped step reduces only through
                # reduce_many_async, which skips the piggybacked dist
                # heartbeat (it would serialize the async dispatch) —
                # keep the worker-skew/last-seen telemetry alive with
                # one heartbeat from the wait side.  `issued` is
                # SPMD-symmetric, so every rank takes this collective
                # together (lockstep contract)
                kv.heartbeat()
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_overlap(len(issued), len(serial),
                                      exposed_s, inflight_s)
        return reduced

    def _bucketed_update(self, plan, reduced):
        """One fused multi-tensor optimizer dispatch per (bucket,
        context); leftover params take the per-param updater."""
        buckets, leftover = plan
        optimizer = self._optimizer
        n_ctx = len(self._contexts)
        for b in buckets:
            # bookkeeping ticks in the exact per-param order (param
            # outer, context inner) so update counts, schedulers and
            # Adam's bias correction see the same sequence
            lrs = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            wds = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            for pos, i in enumerate(b.indices):
                for j in range(n_ctx):
                    lr, wd = opt.fused_lr_wd(optimizer, i, b.kind)
                    lrs[j][pos] = lr
                    wds[j][pos] = wd
            flat = reduced.get(id(b))
            for j in range(n_ctx):
                weights = [self._params[i].list_data()[j]
                           for i in b.indices]
                grads = None if flat is not None else \
                    [self._params[i].list_grad()[j] for i in b.indices]
                opt.fused_bucket_update(optimizer, self._updaters[j],
                                        b.indices, weights, grads,
                                        lrs[j], wds[j], flat_grad=flat)
        for i in leftover:
            param = self._params[i]
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def save_states(self, fname):
        """ref: trainer.py:202 save_states."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                # dist_async: optimizer state lives on the parameter
                # server (same limitation as the reference's PS mode)
                raise ValueError(
                    "Cannot save trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            with open(fname, "wb") as fout:
                fout.write(self._kvstore_obj._updater.get_states(dump_optimizer=True))
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """ref: trainer.py:218 load_states."""
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                raise ValueError(
                    "Cannot load trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            self._kvstore_obj._updater.set_states(states)
            self._kvstore_obj._updater.optimizer.param_dict = {
                i: param for i, param in enumerate(self._params)}
            self._optimizer = self._kvstore_obj._updater.optimizer
        else:
            for updater in self._updaters:
                updater.set_states(states)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: param
                                      for i, param in enumerate(self._params)}
