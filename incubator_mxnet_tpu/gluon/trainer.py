"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py, 238 LoC).

Applies an Optimizer to a set of Parameters. When a KVStore is attached the
gradient path mirrors the reference (trainer.py:156 _update → kvstore
push/pull or update_on_kvstore); on a device mesh the same step lowers to
psum-over-ICI via the parallel package instead of Comm/NCCL reductions.

graftfuse (the bucketed step path): ``step`` no longer walks parameters
one at a time.  Dense float parameters are greedily packed — per dtype,
in tape or index order (``GRAFT_BUCKET_ORDER``, see ``_plan_order``) —
into flat buckets of ~``GRAFT_BUCKET_BYTES`` (default 4 MiB); each
bucket's gradients are concatenated into ONE buffer, reduced across
contexts as one elementwise tree-sum and across workers as one
collective (``KVStore.reduce_many`` → ``_cross_worker_reduce_many``), and
applied through ONE jitted multi-tensor optimizer program per
(optimizer-class, bucket signature) — ``optimizer.fused_bucket_update``.
The whole step stays on device (no ``_read()`` round trips between reduce
and update) and is bit-identical to the per-param path (the fused program
runs the same registered op formulas element-for-element).  Per-param
fallbacks: ``ignore_stale_grad``, sparse grads, and optimizers without a
fused kernel (anything but exact SGD/Adam).  Gradient compression no
longer forces the serial per-key path: ``set_gradient_compression`` and
``GRAFT_QUANT_REDUCE=int8|2bit`` route the BUCKET wire through graftzero's
block-scaled quantization (``parallel.quant``) with error-feedback
residuals kept in the Updater store.  One
behavioral delta on the fused path: reduced gradients are consumed
directly by the update and are NOT written back into
``param.list_grad()`` (``allreduce_grads()`` — the grad-accumulation API
— keeps exact per-key write-back semantics).

graftlap (PR 7) moved each bucket's reduce ISSUE into the backward pass:
``overlap.BucketScheduler`` arms grad-ready hooks at the end of every
bucketed step, the next backward delivers each parameter's gradient the
moment it finalizes, and complete buckets ship through
``KVStore.reduce_many_async`` while the walk continues — ``step()`` only
waits.

graftduplex (PR 9) finishes the wire: the ``update_on_kvstore`` path —
previously 100% serial — gets its own bucket plan (``_duplex_plan``):
bucket reduces ride the same grad-ready hooks mid-backward, the
store-side optimizer applies each bucket's split pieces
(``KVStore.apply_reduced``), and each bucket's weight pull goes straight
back on the wire as a ``PullHandle`` (``KVStore.pull_many_async``)
waited at FIRST USE in the next forward (``overlap.PullScheduler``
first-touch hooks) — the step is full-duplex: gradients stream out
under backward while updated weights stream back under data loading and
the next forward's early layers.  Serial fallbacks mirror the reduce
side: ``GRAFT_OVERLAP_PULL=0``, a stale (user-overwritten) weight
between steps, compression, sparse params; the dist_async parameter
service keeps per-group async pulls (background-thread RPC) without the
bucket plan.
"""
from __future__ import annotations

import os
import time

import numpy as np

from .. import elastic as _elastic
from .. import engine as _engine
from .. import optimizer as opt
from .. import overlap as _overlap
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_DEFAULT_BUCKET_BYTES = _overlap.DEFAULT_BUCKET_BYTES

# back-compat aliases: the bucket/scheduler types moved to overlap.py so
# Module can ride the same machinery (graftduplex)
_Bucket = _overlap.Bucket
_BucketScheduler = _overlap.BucketScheduler


class Trainer(object):
    """ref: gluon/trainer.py class Trainer."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore
        # reference parity (trainer.py update_on_kvstore kwarg): None =
        # auto (store type decides), True/False forces — the switch that
        # selects between the local fused update and the store-side
        # (server-semantics) update the duplex path overlaps
        self._update_on_kvstore_arg = update_on_kvstore
        self._scheduler = _BucketScheduler(self)
        self._pull_scheduler = _overlap.PullScheduler()
        self._bucket_lateness = {}      # param idx -> blocked-wait EWMA
        #                                 (tape-order packing tie-breaker)
        # graftelastic: membership attachment + change listeners; inert
        # (two empty attributes) unless GRAFT_ELASTIC wires them up
        self._membership = None
        self._membership_cbs = []
        # graftpulse: the trainer is a bucket-bytes / bucket-order
        # target for the lens-driven autotuner (weak registration)
        from ..telemetry import autotune as _autotune
        _autotune.register_trainer(self)

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts, " \
                "but Parameter %s is initialized on %s while previous Parameters " \
                "are initialized on %s." % (param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Attach kvstore if requested (ref: trainer.py _init_kvstore)."""
        from .. import kvstore as kvs_mod
        arg_arrays = {param.name: param.data(self._contexts[0])
                      for param in self._params}
        kvstore, update_on_kvstore = kvs_mod.create_kvstore(
            self._kvstore, len(self._contexts), arg_arrays)
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if "dist" in kvstore.type:
                # dist_sync: the store is the in-graph allreduce of GRADS
                # (push then pull grads, update locally).  dist_async: the
                # store IS the weights — the host parameter server applies
                # every push with the server-side optimizer and pulls
                # return weights (kvstore_dist_server.h async mode)
                update_on_kvstore = "async" in kvstore.type
            if self._update_on_kvstore_arg is not None:
                # explicit user choice (reference trainer.py kwarg);
                # dist_async cannot update locally — its weights live on
                # the parameter server (same reference restriction)
                if "async" in kvstore.type \
                        and not self._update_on_kvstore_arg:
                    raise ValueError(
                        "Cannot set update_on_kvstore=False on dist_async")
                update_on_kvstore = bool(self._update_on_kvstore_arg)
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore.init(list(range(len(self._params))),
                         [p.list_data()[0] for p in self._params])
            # pull EVERY param (frozen ones included): on dist stores the
            # init above broadcast rank 0's values, and a frozen layer left
            # at its local random init would make ranks diverge forever
            for i, param in enumerate(self._params):
                kvstore.pull(i, param.list_data(), priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore_obj = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore_obj = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate can be accessed.")
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        """ref: trainer.py set_learning_rate."""
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate is mutated.")
        self._optimizer.lr = lr

    # -- graftelastic: membership fencing -----------------------------------
    def attach_membership(self, membership):
        """Attach this rank's :class:`~..elastic.Membership` state
        machine: ``step()`` becomes its fence — queued membership
        changes apply at the top of the next step, never
        mid-collective."""
        self._membership = membership

    def on_membership_change(self, fn):
        """Register ``fn(view)`` to run after every applied membership
        change (plans already invalidated; ``view`` is the new
        :class:`~..elastic.MembershipView`).  Returns ``fn`` so it
        works as a decorator."""
        self._membership_cbs.append(fn)
        return fn

    def _membership_changed(self, view):
        """The re-partition hook :meth:`~..elastic.Membership.apply_pending`
        calls on this trainer: every world-size-derived artifact —
        fused/duplex bucket plans, the quantizer's store binding, armed
        overlap hooks, in-flight pulls — is dropped and rebuilt lazily
        for the new view on the next step."""
        self._pull_scheduler.finish()
        self._scheduler.disarm()
        self._fused_plan_cache = None
        self._duplex_plan_cache = None
        self._quant_cache = None
        for fn in self._membership_cbs:
            fn(view)

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size
        (ref: trainer.py:156 step).  Takes the bucketed fused path when
        the plan allows it; falls back to the (batched) per-param path
        otherwise — both produce bit-identical parameters."""
        # rescale BEFORE the kvstore handshake: update_on_kvstore ships a
        # pickled optimizer to the server exactly once, so the first
        # step's scaling must already be on it (reference limitation too:
        # later batch-size changes don't reach the server copy)
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        # graftelastic step fence: queued membership changes land HERE —
        # between steps, before this step's plan resolves — so a
        # re-partition can never race a live collective.  Off (the
        # default) this is one memoized env read.
        if _elastic.enabled() and self._membership is not None \
                and self._membership.pending():
            self._membership.apply_pending(trainer=self,
                                           kv=self._kvstore_obj)
        if ignore_stale_grad:
            plan = None
        elif self._update_on_kvstore:
            plan = self._duplex_plan()      # store-side update: duplex
        else:
            plan = self._fused_plan()       # local fused update
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import tracing as _ttracing
        # graftwatch step journal: one flight-recorder event per step
        # with kvstore/update phase latencies + device-memory highwater;
        # a crash or hang mid-step names the phase it stopped in
        overlap = plan is not None and self._overlap_enabled() \
            and self._kvstore_obj is not None
        duplex = self._update_on_kvstore and plan is not None
        with _blackbox.step_journal("trainer", batch_size=batch_size,
                                    fused=plan is not None,
                                    overlapped=overlap, duplex=duplex):
            with _ttracing.phase_span("kvstore"):
                # settle last step's in-flight weight pulls FIRST: an
                # out array rides one handle at a time, and a stale
                # (user-overwritten) weight downgrades THIS round's
                # pulls to the serial path (abandon-and-fallback)
                pull_stale = self._pull_scheduler.finish()
                if plan is None:
                    self._scheduler.disarm()
                    self._allreduce_grads()
                else:
                    reduced = self._bucketed_allreduce(plan)
            with _ttracing.phase_span("update"):
                if plan is None:
                    self._update(ignore_stale_grad,
                                 pull_stale=pull_stale)
                elif duplex:
                    self._duplex_store_update(plan, reduced, pull_stale)
                else:
                    self._bucketed_update(plan, reduced,
                                          pull_stale=pull_stale)
        # graftlap: (re-)arm the grad-ready hooks so the NEXT backward
        # issues each bucket's reduce the moment its grads finalize;
        # first step after any config change runs serial (the plan must
        # exist before hooks know the buckets)
        if overlap:
            self._scheduler.arm(plan)
        elif self._scheduler._armed:
            self._scheduler.disarm()

    def compile_step(self, block, loss=None, enabled=None):
        """graftstep: whole-step compiled training — returns a
        :class:`~.step_compile.CompiledStep` that re-dispatches the
        steady-state ``record → backward → step(batch_size)`` triple for
        ``block`` as ONE donated XLA program (two at a kvstore boundary:
        fwd+bwd → ``reduce_many`` → donated fused update).  Call it in
        place of the triple::

            cstep = trainer.compile_step(net, loss=loss_fn)
            out = cstep(data, label, batch_size=bs)

        Any guard miss (shape/dtype change, param freeze/thaw, optimizer
        hyperparam change — but NOT ``set_learning_rate``, lr rides as a
        traced operand) runs the bit-identical eager triple and
        re-traces lazily.  ``GRAFT_STEP_COMPILE=0`` kill-switches the
        compilation; ``enabled`` overrides the env."""
        from .step_compile import CompiledStep
        return CompiledStep(self, block, loss=loss, enabled=enabled)

    # graftstep pull priority: forward-use order of the params, fed by
    # the compiled-step trace's first-touch hooks (None until recorded)
    _first_touch_order = None

    def note_first_touch_order(self, order):
        """Record the forward first-touch parameter order (trainer param
        indices, first-use first) the compiled-step trace observed.  The
        duplex pull side immediately reorders its pull groups to match
        — the first weights the next forward touches come off the wire
        first — and ``GRAFT_BUCKET_ORDER=touch`` packs buckets by it
        (which re-plans, costing the usual one serial step)."""
        order = tuple(dict.fromkeys(int(i) for i in order
                                    if 0 <= int(i) < len(self._params)))
        if order and order != self._first_touch_order:
            self._first_touch_order = order
            from ..telemetry import blackbox as _blackbox
            _blackbox.record("first_touch_order", n=len(order),
                             head=order[:8])

    def _touch_perm(self, indices):
        """Sort ``indices`` by recorded first-touch order (untouched
        params keep index order, after the touched ones)."""
        pos = {i: k for k, i in enumerate(self._first_touch_order or ())}
        return sorted(indices,
                      key=lambda i: (0, pos[i]) if i in pos else (1, i))

    def allreduce_grads(self):
        """ref: trainer.py allreduce_grads (1.3+, for grad accumulation)."""
        if not self._kv_initialized:
            self._init_kvstore()
        # the accumulation API reduces INTO param.grad() with write-back
        # semantics; anything graftlap issued against the same grads is
        # unrelated to this call — drop it so no bracket stays open
        self._scheduler.disarm()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore_obj is None:
            return
        # one batched multi-key push/pull: a single fused dist collective
        # for the whole gradient set instead of one round per key (the
        # batching role of kvstore_dist.h's big-array sharding)
        keys = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if not keys:
            return
        grads = [self._params[i].list_grad() for i in keys]
        self._kvstore_obj.push_many(keys, grads)
        if not self._update_on_kvstore:
            self._kvstore_obj.pull_many(keys, grads)

    def update(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py update (apply updates without reduce)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False, pull_stale=None):
        if self._kvstore_obj is not None and self._update_on_kvstore:
            if pull_stale is None:      # direct update() call: settle
                pull_stale = self._pull_scheduler.finish()
            keys = [i for i, p in enumerate(self._params)
                    if p.grad_req != "null"]
            if keys:
                self._pull_weights(keys, stale=pull_stale)
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def _pull_overlap_ok(self, keys, stale):
        """Async pulls for this round?  ``stale`` > 0 (a weight the user
        overwrote while its pull was in flight) forces one serial round —
        the abandon-and-fallback rail; sparse params always pull
        serially."""
        return self._overlap_pull_enabled() and not stale \
            and all(self._params[i]._stype == "default" for i in keys)

    def _pull_weights(self, keys, stale=0):
        """Bring updated weights back from the store for ``keys`` —
        async per ~bucket-size group with first-touch waits when the
        duplex pull side is on (graftduplex; the dist_async parameter
        service lands here and overlaps its pull RPC on a background
        thread), the synchronous ``pull_many`` otherwise.

        graftstep pull priority: when a compiled-step trace has recorded
        the forward's first-touch order, pulls issue in that order — the
        weights the next forward consumes first come off the wire first,
        so its first-touch waits land on already-arrived buffers."""
        if self._first_touch_order:
            keys = self._touch_perm(keys)
        _overlap.pull_round(
            self._pull_scheduler, self._kvstore_obj, keys,
            [self._params[i].list_data() for i in keys],
            [int(np.prod(self._params[i].shape))
             * np.dtype(self._params[i].dtype).itemsize for i in keys],
            self._bucket_target_bytes(),
            self._pull_overlap_ok(keys, stale))

    # -- graftfuse: the bucketed step path ---------------------------------
    _bucket_bytes_override = None     # tests/benches force a target here
    _overlap_override = None          # tests/benches force overlap on/off

    def _bucket_target_bytes(self):
        if self._bucket_bytes_override is not None:
            return int(self._bucket_bytes_override)
        try:
            return int(os.environ.get("GRAFT_BUCKET_BYTES",
                                      str(_DEFAULT_BUCKET_BYTES)))
        except ValueError:
            return _DEFAULT_BUCKET_BYTES

    def _overlap_enabled(self):
        """GRAFT_OVERLAP (default on): overlap bucket reduces with the
        backward pass (graftlap).  Like GRAFT_BLACKBOX, multi-host jobs
        must set it IDENTICALLY on every rank — the issue order of the
        overlapped collectives is part of the lockstep contract."""
        if self._overlap_override is not None:
            return bool(self._overlap_override)
        return os.environ.get("GRAFT_OVERLAP", "1").strip().lower() \
            not in ("0", "false", "no", "off")

    _overlap_pull_override = None     # tests/benches force pull overlap

    def _overlap_pull_enabled(self):
        """GRAFT_OVERLAP_PULL (default on): overlap the store→worker
        weight pulls with the next forward (graftduplex).  Same
        rank-consistency contract as GRAFT_OVERLAP."""
        return _overlap.overlap_pull_enabled(self._overlap_pull_override)

    # -- overlap.BucketScheduler host protocol ------------------------------
    _sched_autograd_hooks = True      # hooks delivered by autograd's walk

    def _sched_entries(self, b):
        out = []
        for i in b.indices:
            grads = self._params[i].list_grad()
            for j, d in enumerate(self._params[i].list_data()):
                out.append(((i, j), d, grads[j]))
        return out

    def _sched_eligible(self, b):
        return all(self._params[i].grad_req == "write" for i in b.indices)

    def _sched_kv(self):
        return self._kvstore_obj

    def _sched_flat(self, b):
        return self._bucket_flat(b)

    def _sched_pass_id(self):
        from .. import autograd
        return autograd.backward_pass_id()

    def _sched_label(self, b):
        return "bucket[%s:%dp:%dB]" % (np.dtype(b.dtype).name,
                                       len(b.indices), b.nbytes)

    # -- graftzero: quantized bucket wire + ZeRO-1 sharded update -----------
    def _quant_store(self):
        """The Updater whose ``states`` dict owns the error-feedback
        residuals: the store-side updater when the store runs the update
        (duplex), ``_updaters[0]`` otherwise — either way the store that
        ``save_states``/armor snapshots already serialize."""
        kv = self._kvstore_obj
        if self._update_on_kvstore and kv is not None \
                and kv._updater is not None:
            return kv._updater
        return self._updaters[0]

    def _quantizer(self):
        """The active :class:`~..parallel.quant.BucketQuantizer`, or
        None (quantization off — the bit-identical default path).  The
        env resolution is one dict lookup per call; the quantizer object
        is cached per (mode, block) so toggling re-resolves cleanly."""
        kv = self._kvstore_obj
        if kv is None:
            return None
        from ..parallel import quant as _quant
        mode = _quant.resolve_mode(getattr(kv, "_quant_override", None))
        if mode is None:
            return None
        block = _quant.resolve_block()
        cached = getattr(self, "_quant_cache", None)
        if cached is not None and cached[0] == (mode, block):
            return cached[1]
        q = _quant.BucketQuantizer(mode, block, self._quant_store)
        self._quant_cache = ((mode, block), q)
        return q

    @staticmethod
    def _quant_eligible(b):
        # integer buckets ride the dense wire (their sums are exact)
        return np.issubdtype(np.dtype(b.dtype), np.floating)

    def _sched_reduce_async(self, kv, b, flat):
        """The overlap scheduler's reduce-issue hook: quantize the
        bucket payload onto the wire when the quantized path is on,
        plain ``reduce_many_async`` otherwise — the scheduler itself
        issues quantized buckets unchanged."""
        q = self._quantizer()
        if q is not None and self._quant_eligible(b):
            return q.reduce_async(kv, b, flat,
                                  label=self._sched_label(b))
        return kv.reduce_many_async([flat], label=self._sched_label(b))

    def _zero_spec(self):
        """The ZeRO-1 shard layout this trainer updates under, or None:
        ``GRAFT_SHARD_OPTIMIZER=1`` on the local fused path shards the
        bucket list across contexts (the 8-dev mesh harness) or — with a
        single context on a real dist wire — across worker ranks."""
        from ..parallel import quant as _quant
        if not _quant.zero_enabled():
            return None
        kv = self._kvstore_obj if self._kv_initialized else None
        if kv is None or self._update_on_kvstore:
            return None
        n_ctx = len(self._contexts)
        if n_ctx > 1:
            return {"axis": "ctx", "n": n_ctx, "rank": 0}
        if kv.num_workers > 1:
            return {"axis": "worker", "n": int(kv.num_workers),
                    "rank": int(kv.rank)}
        return None

    def _state_shard_nbytes(self):
        """Max optimizer-state bytes held for one shard owner — what the
        ``graft_trainer_state_shard_bytes`` gauge reports (metadata
        walk, never forces a flush)."""
        return max(u.states_nbytes() for u in self._updaters)

    def _plan_order(self):
        """Parameter iteration order for bucket packing:
        ``(mode, sig_perm, build_perm)``.

        ``GRAFT_BUCKET_ORDER=tape`` (default) sorts parameters by
        DESCENDING earliest-tape-position (``autograd`` stamps
        ``_tape_pos`` on each hooked data array during the backward
        prescan): the reverse walk finalizes high positions first, so
        first-to-finalize params share the first buckets and their
        reduces hit the wire earliest — the overlap window covers more
        of backward (today's index packing often closes the last bucket
        only at end-of-walk).  Parameters without a stamp yet (first
        steps, hook-ineligible) pack after the stamped ones in index
        order.  Ties (params finalized by the same tape node) break on
        the per-param blocked-wait EWMA the step feeds back
        (``_bucket_lateness``, quantized to ms): systematically late
        params pack earlier.  The lateness tie-break applies ONLY when a
        plan is being (re)built — ``sig_perm`` (tape positions + index)
        is what the plan cache keys on, so EWMA drift can never
        invalidate a cached plan and trigger the serial fallback step a
        rebuild costs; a rebuild for a real reason (tape change, shape
        change) picks up the latest lateness.
        ``GRAFT_BUCKET_ORDER=index`` reverts to plain index packing.
        ``GRAFT_BUCKET_ORDER=touch`` packs by the compiled-step trace's
        recorded forward first-touch order (graftstep;
        ``note_first_touch_order``) — untouched params after the touched
        ones in index order, plain index order until a trace has
        recorded anything.  The recorded order is part of ``sig_perm``,
        so a NEW recording re-plans once (the usual one serial step) and
        then stays cached."""
        n = len(self._params)
        mode = _overlap.bucket_order()
        if mode == "touch":
            perm = tuple(self._touch_perm(range(n)))
            return ("touch", perm, perm)
        if mode != "tape":
            perm = tuple(range(n))
            return ("index", perm, perm)
        pos = []
        for p in self._params:
            d = None
            if p._data is not None:
                try:
                    d = p.list_data()[0]
                except Exception:
                    d = None
            pos.append(None if d is None
                       else getattr(d, "_tape_pos", None))
        late = self._bucket_lateness

        def _key(i, with_lateness):
            tp = pos[i]
            if tp is None:
                return (1, 0, 0, i)
            lateness = -int(round(late.get(i, 0.0) * 1e3)) \
                if with_lateness else 0
            return (0, -tp, lateness, i)

        sig_perm = tuple(sorted(range(n), key=lambda i: _key(i, False)))
        build_perm = tuple(sorted(range(n), key=lambda i: _key(i, True)))
        return ("tape", sig_perm, build_perm)

    def _note_bucket_lateness(self, b, blocked_s):
        """Feed one overlapped bucket's blocked wait back into the
        packing tie-breaker (0.8/0.2 EWMA, the straggler convention)."""
        for i in b.indices:
            prev = self._bucket_lateness.get(i)
            self._bucket_lateness[i] = blocked_s if prev is None \
                else 0.8 * prev + 0.2 * blocked_s

    def _duplex_plan(self):
        """The bucket plan for the update_on_kvstore (store-side update)
        path, or None when step() must stay on the serial per-key wire.

        Unlike ``_fused_plan`` the optimizer needs no fused kernel — the
        update runs store-side via ``KVStore.apply_reduced`` with the
        exact per-key updater — so buckets group by dtype alone.
        Fallbacks: no store, the dist_async parameter service (pushes
        must ride the PS RPC; its PULLS still overlap via
        ``_pull_weights``), sparse params, and unknown shapes.
        Compression no longer falls back: the bucket wire quantizes
        through graftzero (block-scaled, error feedback) instead of the
        per-key threshold path it used to force."""
        target = self._bucket_target_bytes()
        kv = self._kvstore_obj
        if target <= 0 or kv is None or not self._update_on_kvstore \
                or getattr(kv, "_ps", None) is not None:
            return None
        order_mode, sig_perm, perm = self._plan_order()
        sig = ("duplex", target, order_mode, sig_perm,
               len(self._contexts),
               tuple((str(p.dtype), p.shape, p.grad_req, p._stype,
                      p._grad_stype) for p in self._params))
        cached = getattr(self, "_duplex_plan_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        open_buckets = {}       # dtype -> (indices, nbytes)
        buckets, leftover = [], []
        for i in perm:
            p = self._params[i]
            if p.grad_req == "null":
                continue
            dense = p._stype == "default" and p._grad_stype == "default"
            known = p.shape is not None and int(np.prod(p.shape)) > 0
            if not dense or not known:
                leftover.append(i)
                continue
            dt = np.dtype(p.dtype)
            nbytes = int(np.prod(p.shape)) * dt.itemsize
            idxs, total = open_buckets.setdefault(dt, ([], 0))
            idxs.append(i)
            total += nbytes
            if total >= target:
                buckets.append(_Bucket(idxs, None, dt, total))
                open_buckets.pop(dt)
            else:
                open_buckets[dt] = (idxs, total)
        for dt, (idxs, total) in open_buckets.items():
            buckets.append(_Bucket(idxs, None, dt, total))
        plan = (buckets, leftover) if buckets else None
        self._duplex_plan_cache = (sig, plan)
        if plan is not None:
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_buckets([b.nbytes for b in buckets],
                                      len(leftover))
        return plan

    def _fused_plan(self):
        """The bucket plan for the current configuration, or None when
        step() must take the per-param path wholesale.  Cached against a
        signature of everything the plan depends on, so steady-state
        steps pay one tuple comparison."""
        target = self._bucket_target_bytes()
        kv = self._kvstore_obj
        if target <= 0 or self._update_on_kvstore \
                or (kv is not None and kv._updater is not None):
            return None
        optimizer = self._optimizer
        # per-param state arity rides in the signature AND the bucket
        # key: existing states keep the formula they were created with
        # (e.g. momentum flipped mid-run only affects states created
        # afterwards, exactly like the per-param path), so a fused
        # program must never mix arities
        states0 = self._updaters[0].states
        kinds, arities = [], []
        for i, p in enumerate(self._params):
            kind = opt.fused_bucket_kind(optimizer, p.dtype) \
                if p.grad_req != "null" else None
            kinds.append(kind)
            arities.append(None if kind is None else (
                opt.fused_state_arity(optimizer, kind, states0[i])
                if i in states0 else opt.fused_state_arity(optimizer, kind)))
        order_mode, sig_perm, perm = self._plan_order()
        sig = (target, type(optimizer), bool(optimizer.multi_precision),
               getattr(optimizer, "momentum", None), tuple(arities),
               len(self._contexts), kv is not None, order_mode, sig_perm,
               tuple((str(p.dtype), p.shape, p.grad_req, p._stype,
                      p._grad_stype) for p in self._params))
        cached = getattr(self, "_fused_plan_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        open_buckets = {}       # (dtype, arity) -> (indices, nbytes)
        buckets, leftover = [], []
        for i in perm:
            p = self._params[i]
            if p.grad_req == "null":
                continue
            kind = kinds[i]
            dense = p._stype == "default" and p._grad_stype == "default"
            known = p.shape is not None and int(np.prod(p.shape)) > 0
            if kind is None or not dense or not known:
                leftover.append(i)
                continue
            dt = np.dtype(p.dtype)
            bkey = (dt, arities[i])
            nbytes = int(np.prod(p.shape)) * dt.itemsize
            idxs, total = open_buckets.setdefault(bkey, ([], 0))
            idxs.append(i)
            total += nbytes
            if total >= target:
                buckets.append(_Bucket(idxs, kind, dt, total))
                open_buckets.pop(bkey)
            else:
                open_buckets[bkey] = (idxs, total)
        for (dt, _arity), (idxs, total) in open_buckets.items():
            buckets.append(_Bucket(idxs, opt.fused_bucket_kind(
                optimizer, dt), dt, total))
        plan = (buckets, leftover) if buckets else None
        self._fused_plan_cache = (sig, plan)
        if plan is not None:
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_buckets([b.nbytes for b in buckets],
                                      len(leftover))
        return plan

    def _bucket_flat(self, b):
        """One bucket's concatenated local gradient — delegates to the
        shared ``overlap.concat_ctx_sum`` packing math (per-context
        flatten + committed-device-safe elementwise tree-sum in context
        order), used verbatim by the serial step path, the overlapped
        mid-backward issue AND Module's bucketed reduce so all of them
        are bit-identical by construction."""
        return _overlap.concat_ctx_sum(
            [[self._params[i].list_grad()[j] for i in b.indices]
             for j in range(len(self._contexts))],
            ctx=self._contexts[0])

    def _bucketed_allreduce(self, plan):
        """Reduce every bucket's gradients with ONE concatenated buffer
        per bucket: contexts tree-sum elementwise (the same addition
        order as KVStore._reduce), workers allreduce through
        ``KVStore.reduce_many`` in one fused collective.  Returns
        {id(bucket): flat reduced NDArray}; empty when there is no store
        (the fused update then reads the per-param grads directly).

        graftlap: buckets whose reduce the scheduler already put on the
        wire mid-backward are only WAITED on here (same buffer, same
        reduction, earlier issue time); buckets that missed the overlap
        window — first step, stale grads, hook fallback — take the
        serial reduce exactly as before.  Wait order is plan order on
        every rank."""
        buckets, leftover = plan
        kv = self._kvstore_obj
        if kv is not None and leftover:
            grads = [self._params[i].list_grad() for i in leftover]
            kv.push_many(leftover, grads)
            if not self._update_on_kvstore:
                kv.pull_many(leftover, grads)
            # update_on_kvstore: the push applied the store-side update;
            # _duplex_store_update pulls the WEIGHTS back (pulling into
            # the grads here would clobber them with weight bytes)
        if kv is None:
            return {}
        overlap = self._overlap_enabled()
        issued = self._scheduler.take(plan) if overlap else {}
        serial = [b for b in buckets if id(b) not in issued]
        flats = {id(b): self._bucket_flat(b) for b in serial}
        q = self._quantizer()
        qb = [b for b in serial
              if q is not None and self._quant_eligible(b)]
        dense = [b for b in serial if id(b) not in {id(x) for x in qb}]
        if qb:
            # graftzero: float buckets ride the block-scaled quantized
            # wire — ONE batched quantized collective, EF residuals in
            # the Updater store, dequantized in place at the boundary
            q.reduce_serial(kv, qb, flats)
        if dense:
            kv.reduce_many([flats[id(b)] for b in dense])
        reduced, exposed_s, inflight_s = {}, 0.0, 0.0
        for b in buckets:
            entry = issued.get(id(b))
            if entry is None:
                reduced[id(b)] = flats[id(b)]
                continue
            flat, handle = entry
            t0 = time.perf_counter()
            handle.wait()
            t1 = time.perf_counter()
            exposed_s += t1 - t0
            inflight_s += t1 - handle.issued_at
            self._note_bucket_lateness(b, t1 - t0)
            reduced[id(b)] = flat
        if overlap:
            if issued:
                # a fully-overlapped step reduces only through
                # reduce_many_async, which skips the piggybacked dist
                # heartbeat (it would serialize the async dispatch) —
                # keep the worker-skew/last-seen telemetry alive with
                # one heartbeat from the wait side.  `issued` is
                # SPMD-symmetric, so every rank takes this collective
                # together (lockstep contract)
                kv.heartbeat()
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_overlap(len(issued), len(serial),
                                      exposed_s, inflight_s)
        return reduced

    def _duplex_store_update(self, plan, reduced, pull_stale=0):
        """The store-side half of the full-duplex step: split each
        bucket's reduced flat into per-key pieces, run the EXACT per-key
        store updater on them (``KVStore.apply_reduced`` — the same
        formula ``push`` would have applied, minus the second reduce),
        and put THAT bucket's weight pull straight back on the wire
        (``_pull_weights`` with the bucket as its own pull group) before
        moving to the next bucket — weights of early buckets stream back
        while later buckets are still updating, and the next forward's
        first-touch hooks absorb the wait.  Leftover (non-bucketable)
        params were pushed serially by ``_bucketed_allreduce``; their
        weights pull serially here."""
        from ..ndarray import NDArray
        buckets, leftover = plan
        kv = self._kvstore_obj
        _overlap.publish_pull_round(self._pull_scheduler)
        all_keys = [i for b in buckets for i in b.indices]
        overlap = self._pull_overlap_ok(all_keys, pull_stale)
        from ..telemetry import lens as _lens
        for b in buckets:
            flat = reduced[id(b)]
            shapes = [self._params[i].shape for i in b.indices]
            pieces = _engine.split_flat(flat._read(), shapes)
            kv.apply_reduced(
                list(b.indices),
                [NDArray(piece, ctx=self._contexts[0])
                 for piece in pieces])
            # graftpulse memory timeline: each bucket's store-side apply
            # is an allocation-watermark sample point
            _lens.mem_sample(self._sched_label(b))
            if overlap:
                # THIS bucket's weights go back on the wire before the
                # next bucket updates — the full-duplex stream
                self._pull_scheduler.issue(
                    kv, list(b.indices),
                    [self._params[i].list_data() for i in b.indices],
                    label="pull[%s:%dp:%dB]" % (np.dtype(b.dtype).name,
                                                len(b.indices), b.nbytes))
        if not overlap and all_keys:
            _overlap.serial_pull(
                kv, all_keys,
                [self._params[i].list_data() for i in all_keys])
        if leftover:
            kv.pull_many(leftover, [self._params[i].list_data()
                                    for i in leftover])

    def _bucketed_update(self, plan, reduced, pull_stale=0):
        """One fused multi-tensor optimizer dispatch per (bucket,
        context); leftover params take the per-param updater.  With
        ``GRAFT_SHARD_OPTIMIZER=1`` (graftzero ZeRO-1) the bucket list
        is sharded: each rank/context runs the fused update — and holds
        optimizer state — only for its contiguous shard, then broadcasts
        the updated weights (byte-identical to the unsharded step)."""
        from ..telemetry import lens as _lens
        shard = self._zero_spec()
        if shard is not None and plan[0]:
            return self._bucketed_update_sharded(plan, reduced, shard,
                                                 pull_stale)
        buckets, leftover = plan
        optimizer = self._optimizer
        n_ctx = len(self._contexts)
        for b in buckets:
            # bookkeeping ticks in the exact per-param order (param
            # outer, context inner) so update counts, schedulers and
            # Adam's bias correction see the same sequence
            lrs = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            wds = [[0.0] * len(b.indices) for _ in range(n_ctx)]
            for pos, i in enumerate(b.indices):
                for j in range(n_ctx):
                    lr, wd = opt.fused_lr_wd(optimizer, i, b.kind)
                    lrs[j][pos] = lr
                    wds[j][pos] = wd
            flat = reduced.get(id(b))
            for j in range(n_ctx):
                weights = [self._params[i].list_data()[j]
                           for i in b.indices]
                grads = None if flat is not None else \
                    [self._params[i].list_grad()[j] for i in b.indices]
                fg = flat
                if flat is not None and j > 0:
                    # replicas commit to distinct devices: the reduced
                    # flat (context 0) must land on context j before the
                    # fused jit sees mixed placements — this transfer IS
                    # the per-context broadcast, bits preserved
                    from ..ndarray import NDArray
                    fg = NDArray(_engine.colocate(flat._read(),
                                                  weights[0]._read()),
                                 ctx=self._contexts[j])
                opt.fused_bucket_update(optimizer, self._updaters[j],
                                        b.indices, weights, grads,
                                        lrs[j], wds[j], flat_grad=fg)
            # graftpulse memory timeline: per-bucket watermark after the
            # fused update dispatch (the future memory planner's signal)
            _lens.mem_sample(self._sched_label(b))
        for i in leftover:
            param = self._params[i]
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def _bucketed_update_sharded(self, plan, reduced, shard, pull_stale=0):
        """graftzero ZeRO-1: contiguous shard ownership over the bucket
        list (``parallel.quant.shard_owners``).  The lr/wd bookkeeping
        ticks in EXACTLY the unsharded (param outer, context inner)
        sequence on every rank — update counts, schedulers and Adam's
        bias correction stay identical — but only the OWNER runs the
        fused update for a bucket, so only the owner ever creates (and
        holds) its optimizer state: per-rank state bytes ~1/N, read off
        the ``graft_trainer_state_shard_bytes`` gauge.  The updated
        weights then broadcast, byte-identical to the unsharded step:

        * axis="ctx" (the device-mesh harness): the owning context
          updates; its weights go through the store's assignment branch
          (``apply_reduced`` — no updater tick) and straight back onto
          the overlapped ``pull_many_async`` wire bucket-by-bucket — a
          reduce-scatter + all-gather over the bucket flats.
        * axis="worker" (dist wire, single ctx): non-owners contribute
          a zeros flat to ONE dense ``reduce_many`` over the updated
          weight flats — an all-gather-by-sum that is exact (0 + x is
          bitwise x, modulo the irrelevant -0.0 + 0.0 corner) and keeps
          every rank's collective sequence lockstep-symmetric.

        Leftover (non-bucketable) params stay unsharded on every rank.
        """
        from ..ndarray import NDArray
        from ..parallel import quant as _quant
        from ..telemetry import lens as _lens
        from ..telemetry import metrics as _tmetrics
        buckets, leftover = plan
        kv = self._kvstore_obj
        optimizer = self._optimizer
        n_ctx = len(self._contexts)
        owners = _quant.shard_owners(len(buckets), shard["n"])
        by_ctx = shard["axis"] == "ctx"
        rank = shard["rank"]
        if by_ctx:
            _overlap.publish_pull_round(self._pull_scheduler)
            all_keys = [i for b in buckets for i in b.indices]
            overlap = self._pull_overlap_ok(all_keys, pull_stale)
        for k, b in enumerate(buckets):
            owner = owners[k]
            lrs = [0.0] * len(b.indices)
            wds = [0.0] * len(b.indices)
            # every (param, context) tick runs so the shared update
            # count advances exactly as in the unsharded loop; the
            # update itself always uses the CONTEXT-0 tick column — the
            # parity target is the unsharded step's context-0 replica
            # (the only well-defined one: Adam's shared per-index count
            # gives each unsharded context its own bias correction)
            for pos, i in enumerate(b.indices):
                for j in range(n_ctx):
                    lr, wd = opt.fused_lr_wd(optimizer, i, b.kind)
                    if j == 0:
                        lrs[pos] = lr
                        wds[pos] = wd
            if by_ctx or owner == rank:
                j = owner if by_ctx else 0
                weights = [self._params[i].list_data()[j]
                           for i in b.indices]
                grads = None if reduced.get(id(b)) is not None else \
                    [self._params[i].list_grad()[j] for i in b.indices]
                fg = reduced.get(id(b))
                if fg is not None and j > 0:
                    fg = NDArray(_engine.colocate(fg._read(),
                                                  weights[0]._read()),
                                 ctx=self._contexts[j])
                opt.fused_bucket_update(optimizer, self._updaters[j],
                                        b.indices, weights, grads,
                                        lrs, wds, flat_grad=fg)
            _lens.mem_sample(self._sched_label(b))
            if by_ctx:
                kv.apply_reduced(
                    list(b.indices),
                    [self._params[i].list_data()[owner]
                     for i in b.indices])
                if overlap:
                    # THIS shard's weights go back on the wire before
                    # the next bucket updates (the duplex stream shape)
                    self._pull_scheduler.issue(
                        kv, list(b.indices),
                        [self._params[i].list_data() for i in b.indices],
                        label="zero_pull[%s:%dp:%dB]" % (
                            np.dtype(b.dtype).name, len(b.indices),
                            b.nbytes))
        if by_ctx and not overlap and all_keys:
            _overlap.serial_pull(
                kv, all_keys,
                [self._params[i].list_data() for i in all_keys])
        if not by_ctx and buckets:
            import jax.numpy as jnp
            wflats = []
            for k, b in enumerate(buckets):
                if owners[k] == rank:
                    vals = [self._params[i].list_data()[0]._read()
                            for i in b.indices]
                    wflats.append(NDArray(_engine.flatten_arrays(vals),
                                          ctx=self._contexts[0]))
                else:
                    ref = reduced[id(b)]
                    wflats.append(NDArray(jnp.zeros_like(ref._read()),
                                          ctx=self._contexts[0]))
            kv.reduce_many(wflats, label="zero_allgather")
            for k, b in enumerate(buckets):
                if owners[k] == rank:
                    continue    # owner keeps its own (identical) bytes
                shapes = [self._params[i].shape for i in b.indices]
                pieces = _engine.split_flat(wflats[k]._read(), shapes)
                for i, piece in zip(b.indices, pieces):
                    tgt = self._params[i].list_data()[0]
                    tgt._write(_engine.colocate(piece, tgt._read()))
        for i in leftover:
            param = self._params[i]
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)
        # per-rank optimizer-state footprint gauge: the acceptance gate
        # for "state bytes ~1/N" reads this
        _tmetrics.trainer_state_shard_bytes(self._state_shard_nbytes(),
                                            shard["n"])
        _lens.mem_sample("zero_shard")

    def save_states(self, fname):
        """ref: trainer.py:202 save_states."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._zero_spec() is not None:
            raise ValueError(
                "save_states cannot serialize a ZeRO-1 sharded trainer "
                "(GRAFT_SHARD_OPTIMIZER=1): each rank/context holds only "
                "its shard of the optimizer state.  Use "
                "trainer.checkpointer(...) — armor snapshots carry the "
                "shard layout and every shard's states.")
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                # dist_async: optimizer state lives on the parameter
                # server (same limitation as the reference's PS mode)
                raise ValueError(
                    "Cannot save trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            with open(fname, "wb") as fout:
                fout.write(self._kvstore_obj._updater.get_states(dump_optimizer=True))
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """ref: trainer.py:218 load_states."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._zero_spec() is not None:
            raise ValueError(
                "load_states cannot restore into a ZeRO-1 sharded trainer "
                "(GRAFT_SHARD_OPTIMIZER=1): a flat states blob has no "
                "shard layout.  Use trainer.checkpointer(...).resume().")
        with open(fname, "rb") as f:
            states = f.read()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                raise ValueError(
                    "Cannot load trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            self._kvstore_obj._updater.set_states(states)
            self._kvstore_obj._updater.optimizer.param_dict = {
                i: param for i, param in enumerate(self._params)}
            self._optimizer = self._kvstore_obj._updater.optimizer
        else:
            for updater in self._updaters:
                updater.set_states(states)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: param
                                      for i, param in enumerate(self._params)}

    # -- graftarmor atomic checkpoint/auto-resume ---------------------------
    def checkpointer(self, directory, every=None, keep=2, emergency=True):
        """A :class:`~incubator_mxnet_tpu.armor.checkpoint.Checkpointer`
        bound to this trainer: call ``ckpt.step_end(step)`` each step for
        periodic (GRAFT_CHECKPOINT_EVERY) atomic snapshots of params +
        optimizer state + step + RNG, ``ckpt.resume(data_iter)`` after a
        restart for last-valid-snapshot auto-resume, and get a
        best-effort emergency snapshot from the SIGTERM hook for free."""
        from ..armor.checkpoint import Checkpointer
        return Checkpointer(self, directory, every=every, keep=keep,
                            emergency=emergency)

    def save_checkpoint(self, path, step=0):
        """One atomic full-state snapshot (params + optimizer states +
        ``step`` + RNG) at ``path`` — in-flight async pushes/pulls are
        drained first so the snapshot is step-consistent.  See
        :mod:`~incubator_mxnet_tpu.armor.checkpoint`."""
        from ..armor import checkpoint as _ckpt
        return _ckpt.save_state(path, _ckpt.snapshot_trainer(self, step))

    def load_checkpoint(self, path):
        """Restore a :meth:`save_checkpoint` snapshot (validated against
        its embedded hash; raises ``CheckpointCorruptError`` on damage);
        returns the step the snapshot was taken at."""
        from ..armor import checkpoint as _ckpt
        state = _ckpt.load_state(path)
        _ckpt.restore_trainer(self, state)
        return int(state.get("step", 0))
