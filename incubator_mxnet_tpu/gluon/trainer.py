"""Gluon Trainer (ref: python/mxnet/gluon/trainer.py, 238 LoC).

Applies an Optimizer to a set of Parameters. When a KVStore is attached the
gradient path mirrors the reference (trainer.py:156 _update → kvstore
push/pull or update_on_kvstore); on a device mesh the same step lowers to
psum-over-ICI via the parallel package instead of Comm/NCCL reductions.
"""
from __future__ import annotations

from .. import optimizer as opt
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer(object):
    """ref: gluon/trainer.py class Trainer."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                "got %s." % (type(params)))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % (type(param)))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = optimizer_params.get("rescale_grad", 1.0)
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = kvstore

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            assert contexts is None or contexts == ctx, \
                "All Parameters must be initialized on the same set of contexts, " \
                "but Parameter %s is initialized on %s while previous Parameters " \
                "are initialized on %s." % (param.name, str(ctx), str(contexts))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        """Attach kvstore if requested (ref: trainer.py _init_kvstore)."""
        from .. import kvstore as kvs_mod
        arg_arrays = {param.name: param.data(self._contexts[0])
                      for param in self._params}
        kvstore, update_on_kvstore = kvs_mod.create_kvstore(
            self._kvstore, len(self._contexts), arg_arrays)
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if "dist" in kvstore.type:
                # dist_sync: the store is the in-graph allreduce of GRADS
                # (push then pull grads, update locally).  dist_async: the
                # store IS the weights — the host parameter server applies
                # every push with the server-side optimizer and pulls
                # return weights (kvstore_dist_server.h async mode)
                update_on_kvstore = "async" in kvstore.type
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore.init(list(range(len(self._params))),
                         [p.list_data()[0] for p in self._params])
            # pull EVERY param (frozen ones included): on dist stores the
            # init above broadcast rank 0's values, and a frozen layer left
            # at its local random init would make ranks diverge forever
            for i, param in enumerate(self._params):
                kvstore.pull(i, param.list_data(), priority=-i)
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            self._kvstore_obj = kvstore
            self._update_on_kvstore = update_on_kvstore
        else:
            self._kvstore_obj = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate can be accessed.")
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        """ref: trainer.py set_learning_rate."""
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its learning "
                              "rate is mutated.")
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with grads scaled by 1/batch_size
        (ref: trainer.py:156 step)."""
        # rescale BEFORE the kvstore handshake: update_on_kvstore ships a
        # pickled optimizer to the server exactly once, so the first
        # step's scaling must already be on it (reference limitation too:
        # later batch-size changes don't reach the server copy)
        self._optimizer.rescale_grad = self._scale / batch_size
        if not self._kv_initialized:
            self._init_kvstore()
        from ..telemetry import tracing as _ttracing
        with _ttracing.phase_span("kvstore"):
            self._allreduce_grads()
        with _ttracing.phase_span("update"):
            self._update(ignore_stale_grad)

    def allreduce_grads(self):
        """ref: trainer.py allreduce_grads (1.3+, for grad accumulation)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore_obj is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore_obj.push(i, param.list_grad(), priority=-i)
                if not self._update_on_kvstore:
                    self._kvstore_obj.pull(i, param.list_grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        """ref: trainer.py update (apply updates without reduce)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._kvstore_obj is not None and self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore_obj.pull(i, param.list_data(), priority=-i)
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            for upd, arr, grad in zip(self._updaters, param.list_data(),
                                      param.list_grad()):
                upd(i, grad, arr)

    def save_states(self, fname):
        """ref: trainer.py:202 save_states."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                # dist_async: optimizer state lives on the parameter
                # server (same limitation as the reference's PS mode)
                raise ValueError(
                    "Cannot save trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            with open(fname, "wb") as fout:
                fout.write(self._kvstore_obj._updater.get_states(dump_optimizer=True))
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """ref: trainer.py:218 load_states."""
        if not self._kv_initialized:
            self._init_kvstore()
        with open(fname, "rb") as f:
            states = f.read()
        if self._update_on_kvstore:
            if self._kvstore_obj._updater is None:
                raise ValueError(
                    "Cannot load trainer states when the optimizer runs "
                    "on the parameter server (dist_async)")
            self._kvstore_obj._updater.set_states(states)
            self._kvstore_obj._updater.optimizer.param_dict = {
                i: param for i, param in enumerate(self._params)}
            self._optimizer = self._kvstore_obj._updater.optimizer
        else:
            for updater in self._updaters:
                updater.set_states(states)
            self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {i: param
                                      for i, param in enumerate(self._params)}
