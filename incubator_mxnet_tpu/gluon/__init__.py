"""Gluon: the imperative layer API (ref: python/mxnet/gluon/__init__.py).

Block/HybridBlock with jit hybridization, Parameter/ParameterDict, Trainer,
losses, nn/rnn layers, data pipeline, model zoo — the full Gluon surface of
the reference, TPU-native (see gluon/block.py for the CachedOp design).
"""
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from .step_compile import CompiledStep, step_compile_enabled
from . import nn
from . import loss
from . import utils
from . import data
from . import rnn
from . import model_zoo
from . import contrib
