"""Basic Gluon layers (ref: python/mxnet/gluon/nn/basic_layers.py).

Same layer set and parameter naming as the reference: Sequential,
HybridSequential, Dense, Activation, Dropout, BatchNorm, InstanceNorm,
LayerNorm, Embedding, Flatten, Lambda, HybridLambda.  All compute lowers to
registry ops (XLA kernels); hybridize() compiles whole stacks into one jit.
"""
from __future__ import annotations

import numpy as np

from ..block import Block, HybridBlock
from ... import initializer
from ...ndarray import NDArray

__all__ = ["Sequential", "HybridSequential", "Dense", "Activation", "Dropout",
           "BatchNorm", "InstanceNorm", "LayerNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda"]


class Sequential(Block):
    """Stack of Blocks (ref: basic_layers.py class Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children:
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(key=key, block=block)
                           for key, block in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        return self._children[key]

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        """ref: basic_layers.py Sequential.hybridize warning-free passthrough."""
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, hybridizable as one graph
    (ref: basic_layers.py class HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children:
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(key=key, block=block)
                           for key, block in enumerate(self._children))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        return self._children[key]

    def __len__(self):
        return len(self._children)


class Dense(HybridBlock):
    """Fully-connected layer y = act(x·Wᵀ + b)
    (ref: basic_layers.py class Dense → FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          dtype=dtype,
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,), dtype=dtype,
                                            init=initializer.create(bias_initializer)
                                            if isinstance(bias_initializer, str)
                                            else bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _pre_infer(self, x):
        if self.weight.shape and self.weight.shape[1] == 0:
            in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        else:
            act = F.FullyConnected(x, weight, bias, no_bias=False,
                                   num_hidden=self._units,
                                   flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return "{name}({layout}, {act})".format(
            name=self.__class__.__name__,
            act=self.act if self.act else "linear",
            layout="{0} -> {1}".format(shape[1] if shape[1] else None, shape[0]))


class Activation(HybridBlock):
    """ref: basic_layers.py class Activation → Activation op."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "{name}({_act_type})".format(name=self.__class__.__name__,
                                            **{"_act_type": self._act_type})


class Dropout(HybridBlock):
    """ref: basic_layers.py class Dropout → Dropout op (inverted, train-only)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return "{name}(p = {_rate}, axes={_axes})".format(
            name=self.__class__.__name__, _rate=self._rate, _axes=self._axes)


class BatchNorm(HybridBlock):
    """Batch normalization with moving stats (ref: basic_layers.py BatchNorm).

    Moving mean/var update happens front-end-side from the op's batch-stat
    outputs — under hybridization the in-place write is harvested from the
    trace and applied after the jit call (see gluon/block.py CachedOp).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=initializer.create(gamma_initializer)
                                     if isinstance(gamma_initializer, str) else gamma_initializer,
                                     allow_deferred_init=True,
                                     differentiable=scale)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=initializer.create(beta_initializer)
                                    if isinstance(beta_initializer, str) else beta_initializer,
                                    allow_deferred_init=True,
                                    differentiable=center)
        self.running_mean = self.params.get("running_mean", grad_req="null",
                                            shape=(in_channels,),
                                            init=initializer.create(running_mean_initializer)
                                            if isinstance(running_mean_initializer, str)
                                            else running_mean_initializer,
                                            allow_deferred_init=True,
                                            differentiable=False)
        self.running_var = self.params.get("running_var", grad_req="null",
                                           shape=(in_channels,),
                                           init=initializer.create(running_variance_initializer)
                                           if isinstance(running_variance_initializer, str)
                                           else running_variance_initializer,
                                           allow_deferred_init=True,
                                           differentiable=False)

    def _pre_infer(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p.shape == (0,):
                p.shape = (c,)

    def cast(self, dtype):
        if np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd
        out = F.BatchNorm(x, gamma, beta, running_mean, running_var,
                          output_mean_var=True, **self._kwargs)
        if isinstance(out, (list, tuple)):
            y, batch_mean, batch_var = out
            if autograd.is_training() and not self._kwargs["use_global_stats"]:
                m = self._momentum
                with autograd.pause():
                    running_mean._write(
                        m * running_mean._read()
                        + (1 - m) * batch_mean.detach()._read())
                    running_var._write(
                        m * running_var._read()
                        + (1 - m) * batch_var.detach()._read())
            return y
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__, in_channels=in_channels,
            content=", ".join("=".join([k, v.__repr__()])
                              for k, v in self._kwargs.items()))


class InstanceNorm(HybridBlock):
    """ref: basic_layers.py class InstanceNorm → InstanceNorm op."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=initializer.create(gamma_initializer)
                                     if isinstance(gamma_initializer, str) else gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=initializer.create(beta_initializer)
                                    if isinstance(beta_initializer, str) else beta_initializer,
                                    allow_deferred_init=True)

    def _pre_infer(self, x):
        c = x.shape[1]
        for p in (self.gamma, self.beta):
            if p.shape == (0,):
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, **self._kwargs)


class LayerNorm(HybridBlock):
    """Layer normalization (ref: src/operator/nn/layer_norm.cc; gluon layer
    appears in 1.3 — included for the transformer stack)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon}
        self._axis = axis
        self.gamma = self.params.get("gamma",
                                     grad_req="write" if scale else "null",
                                     shape=(in_channels,),
                                     init=initializer.create(gamma_initializer)
                                     if isinstance(gamma_initializer, str) else gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta",
                                    grad_req="write" if center else "null",
                                    shape=(in_channels,),
                                    init=initializer.create(beta_initializer)
                                    if isinstance(beta_initializer, str) else beta_initializer,
                                    allow_deferred_init=True)

    def _pre_infer(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta):
            if p.shape == (0,):
                p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, **self._kwargs)


class Embedding(HybridBlock):
    """Index → vector lookup (ref: basic_layers.py class Embedding →
    Embedding op; rowsparse grad becomes a dense scatter-add on TPU)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                      init=weight_initializer, dtype=dtype,
                                      allow_deferred_init=True)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class Flatten(HybridBlock):
    """ref: basic_layers.py class Flatten → Flatten op."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class Lambda(Block):
    """Wrap a function as a Block (ref: basic_layers.py class Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: {} of type {}"
                             .format(function, type(function)))

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)


class HybridLambda(HybridBlock):
    """Wrap a function as a HybridBlock (ref: basic_layers.py HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd
            assert hasattr(nd, function), \
                "Function name %s is not found in ndarray." % function
            func = getattr(nd, function)
            self._func = lambda F, *args: func(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: {} of type {}"
                             .format(function, type(function)))

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return "{name}({function})".format(name=self.__class__.__name__,
                                           function=self._func_name)
