"""Attention layers on the Gluon surface.

The reference (2018-era) has no attention layer; SURVEY §2.4/§5.7 mandate
sequence/context parallelism as a first-class capability of the TPU
rebuild.  ``MultiHeadAttention`` is the user-facing block: plain flash
attention on one device, and with ``seq_axis="sp"`` the SAME layer runs
exact ring attention over the scoped mesh's sequence axis — long-context
training without leaving the Gluon API (the gap called out by the round-2
review: ring attention existed only as a raw jax function).
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from .basic_layers import Dense

__all__ = ["MultiHeadAttention"]


class MultiHeadAttention(HybridBlock):
    """Multi-head self/cross attention (B, S, E) -> (B, S, E).

    Parameters
    ----------
    units : int
        Total embedding width E (split across heads).
    num_heads : int
        Head count H; head dim D = E // H.
    causal : bool
        Autoregressive masking.
    seq_axis : str or None
        None — flash attention on the local device
        (ops/attention.py Pallas kernel / lax fallback).
        An axis name (e.g. ``"sp"``) — exact ring attention with the
        sequence sharded over that axis of the mesh in the enclosing
        ``parallel.use_mesh`` scope; K/V shards rotate over ICI
        (parallel/ring_attention.py).  Same math, same layer, chosen per
        deployment.
    use_bias : bool
        Bias on the q/k/v/out projections.
    fused_qkv : bool
        Project q/k/v with ONE (E, 3E) matmul instead of three (E, E)
        ones (self-attention only).  On the MXU a single wide matmul
        sustains far higher throughput than three narrow ones (measured
        ~197 vs ~80 TFLOP/s at E=4096 on v5e), and XLA does not fuse the
        three projections itself.
    """

    def __init__(self, units, num_heads, causal=False, seq_axis=None,
                 use_bias=True, fused_qkv=False, weight_initializer=None,
                 **kwargs):
        super().__init__(**kwargs)
        if units % num_heads:
            raise ValueError("units (%d) must be divisible by num_heads (%d)"
                             % (units, num_heads))
        self._units = units
        self._num_heads = num_heads
        self._causal = bool(causal)
        self._seq_axis = seq_axis
        self._fused_qkv = bool(fused_qkv)
        with self.name_scope():
            if self._fused_qkv:
                self.proj_qkv = Dense(3 * units, flatten=False,
                                      use_bias=use_bias,
                                      weight_initializer=weight_initializer,
                                      prefix="qkv_")
            else:
                self.proj_q = Dense(units, flatten=False, use_bias=use_bias,
                                    weight_initializer=weight_initializer,
                                    prefix="q_")
                self.proj_k = Dense(units, flatten=False, use_bias=use_bias,
                                    weight_initializer=weight_initializer,
                                    prefix="k_")
                self.proj_v = Dense(units, flatten=False, use_bias=use_bias,
                                    weight_initializer=weight_initializer,
                                    prefix="v_")
            self.proj_out = Dense(units, flatten=False, use_bias=use_bias,
                                  weight_initializer=weight_initializer,
                                  prefix="out_")

    def _split_heads(self, F, x, B, S):
        # (B, S, E) -> (B, H, S, D)
        x = F.reshape(x, shape=(B, S, self._num_heads, -1))
        return F.transpose(x, axes=(0, 2, 1, 3))

    def hybrid_forward(self, F, query, key=None, value=None):
        if self._fused_qkv and (key is not None or value is not None):
            raise ValueError("fused_qkv supports self-attention only "
                             "(pass just the query)")
        key = query if key is None else key
        value = key if value is None else value
        B, S = query.shape[0], query.shape[1]
        Sk = key.shape[1]
        if self._fused_qkv:
            qkv = self.proj_qkv(query)                   # (B, S, 3E)
            E = self._units
            q = self._split_heads(
                F, F.slice_axis(qkv, axis=-1, begin=0, end=E), B, S)
            k = self._split_heads(
                F, F.slice_axis(qkv, axis=-1, begin=E, end=2 * E), B, Sk)
            v = self._split_heads(
                F, F.slice_axis(qkv, axis=-1, begin=2 * E, end=3 * E), B, Sk)
        else:
            q = self._split_heads(F, self.proj_q(query), B, S)
            k = self._split_heads(F, self.proj_k(key), B, Sk)
            v = self._split_heads(F, self.proj_v(value), B, Sk)
        scale = 1.0 / float(np.sqrt(self._units // self._num_heads))
        if self._seq_axis is None:
            out = F._contrib_FlashAttention(q, k, v, causal=self._causal,
                                            scale=scale)
        else:
            out = F._contrib_RingAttention(q, k, v, seq_axis=self._seq_axis,
                                           causal=self._causal, scale=scale)
        out = F.transpose(out, axes=(0, 2, 1, 3))
        out = F.reshape(out, shape=(B, S, self._units))
        return self.proj_out(out)
