"""Gluon neural-network layers (ref: python/mxnet/gluon/nn/__init__.py)."""
from ..block import Block, HybridBlock, SymbolBlock
from .basic_layers import *
from .conv_layers import *
from .attention import *
