"""Convolution and pooling Gluon layers (ref: python/mxnet/gluon/nn/conv_layers.py).

Same API surface as the reference (Conv1D/2D/3D, Conv*DTranspose,
Max/Avg/GlobalMax/GlobalAvg pooling); compute lowers to the Convolution /
Deconvolution / Pooling registry ops, i.e. XLA convolutions tiling straight
onto the MXU (no im2col, no cuDNN algorithm selection — XLA autotunes).
"""
from __future__ import annotations

import numpy as np

from ..block import HybridBlock
from ... import initializer
from .basic_layers import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _to_tuple(v, n):
    if isinstance(v, (tuple, list)):
        assert len(v) == n
        return tuple(v)
    return (v,) * n


class _Conv(HybridBlock):
    """Base conv layer (ref: conv_layers.py class _Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            nd_ = len(kernel_size)
            self._op_name = op_name
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "dilate": dilation,
                "pad": padding, "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias, "layout": layout}
            if adj is not None:
                self._kwargs["adj"] = adj

            # weight shape: OIHW for conv, IOHW for deconv (ref:
            # deconvolution-inl.h stores (in, out/groups, *k))
            if op_name == "Deconvolution":
                wshapes = [in_channels, channels // groups] + list(kernel_size)
            else:
                wshapes = [channels, in_channels // groups] + list(kernel_size)
            self.weight = self.params.get("weight", shape=tuple(wshapes),
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,),
                    init=initializer.create(bias_initializer)
                    if isinstance(bias_initializer, str) else bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _alias(self):
        return "conv"

    def _pre_infer(self, x):
        in_channels = x.shape[1]
        if self._op_name == "Deconvolution":
            if self.weight.shape and self.weight.shape[0] == 0:
                self.weight.shape = tuple(
                    [in_channels, self._channels // self._kwargs["num_group"]]
                    + list(self._kwargs["kernel"]))
        elif self.weight.shape and self.weight.shape[1] == 0:
            w = list(self.weight.shape)
            w[1] = in_channels // self._kwargs["num_group"]
            self.weight.shape = tuple(w)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, **self._kwargs)
        else:
            act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if self._kwargs["num_group"] != 1:
            s += ", groups={num_group}"
        if self.bias is None:
            s += ", bias=False"
        s += ")"
        shape = self.weight.shape
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(shape[1] if shape[1] else None,
                                                    shape[0]),
                        **self._kwargs)


class Conv1D(_Conv):
    """ref: conv_layers.py class Conv1D (NCW)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 1)
        super().__init__(channels, kernel_size, _to_tuple(strides, 1),
                         _to_tuple(padding, 1), _to_tuple(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """ref: conv_layers.py class Conv2D (NCHW)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 2)
        super().__init__(channels, kernel_size, _to_tuple(strides, 2),
                         _to_tuple(padding, 2), _to_tuple(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """ref: conv_layers.py class Conv3D (NCDHW)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 3)
        super().__init__(channels, kernel_size, _to_tuple(strides, 3),
                         _to_tuple(padding, 3), _to_tuple(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv1DTranspose(_Conv):
    """ref: conv_layers.py class Conv1DTranspose."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 1)
        super().__init__(channels, kernel_size, _to_tuple(strides, 1),
                         _to_tuple(padding, 1), _to_tuple(dilation, 1), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 1), **kwargs)
        self.outpad = _to_tuple(output_padding, 1)


class Conv2DTranspose(_Conv):
    """ref: conv_layers.py class Conv2DTranspose."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 2)
        super().__init__(channels, kernel_size, _to_tuple(strides, 2),
                         _to_tuple(padding, 2), _to_tuple(dilation, 2), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 2), **kwargs)
        self.outpad = _to_tuple(output_padding, 2)


class Conv3DTranspose(_Conv):
    """ref: conv_layers.py class Conv3DTranspose."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0), dilation=(1, 1, 1),
                 groups=1, layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 3)
        super().__init__(channels, kernel_size, _to_tuple(strides, 3),
                         _to_tuple(padding, 3), _to_tuple(dilation, 3), groups,
                         layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution",
                         adj=_to_tuple(output_padding, 3), **kwargs)
        self.outpad = _to_tuple(output_padding, 3)


class _Pooling(HybridBlock):
    """Base pooling (ref: conv_layers.py class _Pooling → Pooling op)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        if isinstance(strides, int):
            strides = (strides,) * len(pool_size)
        if isinstance(padding, int):
            padding = (padding,) * len(pool_size)
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}, " \
            "ceil_mode={ceil_mode})".format(
                name=self.__class__.__name__,
                ceil_mode=self._kwargs["pooling_convention"] == "full",
                **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW", "Only supports NCW layout for now"
        super().__init__(_to_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCHW", "Only supports NCHW layout for now"
        super().__init__(_to_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 ceil_mode=False, layout="NCDHW", **kwargs):
        assert layout == "NCDHW", "Only supports NCDHW layout for now"
        super().__init__(_to_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCW", "Only supports NCW layout for now"
        super().__init__(_to_tuple(pool_size, 1), strides, padding, ceil_mode,
                         False, "avg", **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        assert layout == "NCHW", "Only supports NCHW layout for now"
        super().__init__(_to_tuple(pool_size, 2), strides, padding, ceil_mode,
                         False, "avg", **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 ceil_mode=False, layout="NCDHW", **kwargs):
        assert layout == "NCDHW", "Only supports NCDHW layout for now"
        super().__init__(_to_tuple(pool_size, 3), strides, padding, ceil_mode,
                         False, "avg", **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "max", **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, 0, True, True, "avg", **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, 0, True, True, "avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, 0, True, True, "avg", **kwargs)
