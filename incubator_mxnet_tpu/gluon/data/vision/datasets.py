"""Vision datasets (ref: python/mxnet/gluon/data/vision/datasets.py).

MNIST/FashionMNIST read the standard idx gzip files, CIFAR10/100 the binary
batches — from a local root (no network egress in this environment; point
`root` at pre-downloaded files).  ImageRecordDataset/ImageFolderDataset
mirror the reference's record/folder pipelines.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from .... import ndarray as _nd
from .... import config as _config
from ..dataset import Dataset, RecordFileDataset


def _default_root(name):
    """Dataset cache dir under MXTPU_HOME (default ~/.mxnet/datasets)."""
    return os.path.join(_config.data_home(), "datasets", name)

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    """ref: datasets.py _DownloadedDataset."""

    def __init__(self, root, transform):
        super().__init__()
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from idx files (ref: datasets.py class MNIST)."""

    _train_data = "train-images-idx3-ubyte.gz"
    _train_label = "train-labels-idx1-ubyte.gz"
    _test_data = "t10k-images-idx3-ubyte.gz"
    _test_label = "t10k-labels-idx1-ubyte.gz"

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        super().__init__(root or _default_root("mnist"), transform)

    def _get_data(self):
        if self._train:
            data_file = os.path.join(self._root, self._train_data)
            label_file = os.path.join(self._root, self._train_label)
        else:
            data_file = os.path.join(self._root, self._test_data)
            label_file = os.path.join(self._root, self._test_label)
        for f in (data_file, label_file):
            alt = f[:-3]  # allow non-gz
            if not os.path.exists(f) and not os.path.exists(alt):
                raise IOError(
                    "%s not found. This environment has no network egress; "
                    "place the MNIST idx files under %s." % (f, self._root))

        def _open(path):
            if os.path.exists(path):
                return gzip.open(path, "rb")
            return open(path[:-3], "rb")

        with _open(label_file) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.int32)
        with _open(data_file) as fin:
            struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(len(label), 28, 28, 1)
        self._data = _nd.array(data, dtype=data.dtype)
        self._label = label


class FashionMNIST(MNIST):
    """ref: datasets.py class FashionMNIST (same idx format)."""

    def __init__(self, root=None, train=True, transform=None):
        super().__init__(root or _default_root("fashion-mnist"),
                         train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 binary batches (ref: datasets.py class CIFAR10)."""

    _archive_members = ["data_batch_1.bin", "data_batch_2.bin",
                        "data_batch_3.bin", "data_batch_4.bin",
                        "data_batch_5.bin"]
    _test_member = "test_batch.bin"
    _rec_size = 3073

    def __init__(self, root=None, train=True, transform=None):
        self._train = train
        super().__init__(root or _default_root("cifar10"), transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        data = raw.reshape(-1, self._rec_size)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(np.int32)

    def _get_data(self):
        files = self._archive_members if self._train else [self._test_member]
        paths = [os.path.join(self._root, f) for f in files]
        # also allow the cifar-10-batches-bin subdir layout
        alt = os.path.join(self._root, "cifar-10-batches-bin")
        if not os.path.exists(paths[0]) and os.path.isdir(alt):
            paths = [os.path.join(alt, f) for f in files]
        for p in paths:
            if not os.path.exists(p):
                raise IOError(
                    "%s not found. This environment has no network egress; "
                    "place the CIFAR-10 binary batches under %s." % (p, self._root))
        data, label = zip(*[self._read_batch(p) for p in paths])
        data = np.concatenate(data)
        label = np.concatenate(label)
        self._data = _nd.array(data, dtype=data.dtype)
        self._label = label


class CIFAR100(CIFAR10):
    """ref: datasets.py class CIFAR100."""

    _rec_size = 3074

    def __init__(self, root=None, fine_label=False, train=True,
                 transform=None):
        self._fine_label = fine_label
        self._archive_members = ["train.bin"]
        self._test_member = "test.bin"
        super().__init__(root=root or _default_root("cifar100"),
                         train=train, transform=transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        data = raw.reshape(-1, self._rec_size)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 if not self._fine_label else 1].astype(np.int32)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a RecordIO file (ref: datasets.py
    ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import recordio, image
        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """folder/label/img.jpg layout (ref: datasets.py ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from .... import image
        with open(self.items[idx][0], "rb") as f:
            img = image.imdecode(f.read(), self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
