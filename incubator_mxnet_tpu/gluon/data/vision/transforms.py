"""Vision transforms as Blocks (ref: gluon/data/vision/transforms.py appears
in 1.3; included because Gluon vision training needs them — ToTensor,
Normalize, Resize, crops, flips — lowered to the image ops
(src/operator/image/ in the reference)."""
from __future__ import annotations

import numpy as np

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from ....ndarray import NDArray
from .... import ndarray as _nd

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom"]


class Compose(Sequential):
    """Sequential transform composition (ref: transforms.py Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for i in transforms:
            if isinstance(i, Block):
                self.add(i)
            else:
                self.add(Lambda_(i))


class Lambda_(Block):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, x):
        return self._fn(x)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 → CHW float32 /255 (ref: image/to_tensor op)."""

    def hybrid_forward(self, F, x):
        out = x.astype("float32") / 255.0
        return F.transpose(out, axes=(2, 0, 1))


class Normalize(HybridBlock):
    """(x - mean) / std per channel on CHW input."""

    def __init__(self, mean, std):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = _nd.array(self._mean, ctx=x.context)
        std = _nd.array(self._std, ctx=x.context)
        return (x - mean) / std


class Resize(Block):
    """Resize HWC image (bilinear via jax.image.resize)."""

    def __init__(self, size, keep_ratio=False):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        import jax
        h, w = self._size[1], self._size[0]
        v = x._read().astype("float32")
        out = jax.image.resize(v, (h, w, v.shape[2]), method="bilinear")
        return NDArray(out.astype(x._read().dtype), ctx=x.context)


class CenterCrop(Block):
    def __init__(self, size):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        y0 = max((H - h) // 2, 0)
        x0 = max((W - w) // 2, 0)
        return NDArray(x._read()[y0:y0 + h, x0:x0 + w], ctx=x.context)


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        import jax
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            aspect = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target_area * aspect)))
            h = int(round(np.sqrt(target_area / aspect)))
            if w <= W and h <= H:
                x0 = np.random.randint(0, W - w + 1)
                y0 = np.random.randint(0, H - h + 1)
                crop = x._read()[y0:y0 + h, x0:x0 + w].astype("float32")
                out = jax.image.resize(
                    crop, (self._size[1], self._size[0], crop.shape[2]),
                    method="bilinear")
                return NDArray(out.astype(x._read().dtype), ctx=x.context)
        return CenterCrop(self._size).forward(x)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return NDArray(x._read()[:, ::-1], ctx=x.context)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return NDArray(x._read()[::-1], ctx=x.context)
        return x
