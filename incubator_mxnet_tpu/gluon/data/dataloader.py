"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes and ships batches through POSIX
shared-memory NDArrays (dataloader.py:72 rebuild_ndarray).  The TPU-native
pipeline keeps augmentation on host CPU in a thread pool — numpy transforms
release the GIL, jax.device_put overlaps H2D with compute — and hands the
device exactly one ready batch ahead (double-buffering, the same effect the
reference's prefetcher iterators achieve: src/io/iter_prefetcher.h).

graftduplex prefetch-to-device (GRAFT_PREFETCH_DEVICE, default on): each
lookahead batch's host→device transfer is ISSUED on the worker thread
under ``engine.offband()`` the moment the batch is built
(``io.issue_device_prefetch`` — the same issue/wait split ``ReduceHandle``
gave the gradient wire), so batch N+1's bytes stream to the device while
batch N computes.  With ``num_workers=0`` the loader now runs the same
one-batch-lookahead pipeline on a single pool thread (batches stay
sequential and in order — the reference's prefetcher iterators thread the
"synchronous" path the same way, iter_prefetcher.h); set
``GRAFT_PREFETCH_DEVICE=0`` or ``prefetch_device=False`` for the strictly
consumer-thread behavior.
"""
from __future__ import annotations

import os

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...io import device_prefetch_enabled, issue_device_prefetch
from ...ndarray import NDArray
from ... import ndarray as _nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return _nd.ndarray.concatenate([d.expand_dims(0) for d in data], axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return _nd.array(data, dtype=data.dtype)


def prefetch_depth_default():
    """GRAFT_PREFETCH_DEPTH (default 2, floor 1): how many lookahead
    batches the pooled pipeline keeps in flight beyond what the worker
    count implies.  2 is classic double-buffering; deeper absorbs
    per-batch build-time variance (one slow batch no longer stalls the
    consumer) at the cost of that many batches resident on host.  The
    graftpulse autotuner grows a loader's LIVE depth past this default
    when worker growth alone can't close a ``data_wait`` signal."""
    try:
        v = int(os.environ.get("GRAFT_PREFETCH_DEPTH", "2"))
    except ValueError:
        v = 2
    return max(1, v)


class DataLoader(object):
    """Loads batches from a Dataset (ref: dataloader.py class DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, prefetch_device=None):
        self._dataset = dataset
        self._prefetch_device = prefetch_device     # None = GRAFT_PREFETCH_DEVICE
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers
        self._prefetch_depth = None     # None = GRAFT_PREFETCH_DEPTH
        self._pool = None       # lazily-created per-loader worker pool
        self._blocked_wait_s = 0.0      # cumulative consumer-blocked wait
        #                                 (the autotuner ranks loaders by
        #                                 its growth to grow the one that
        #                                 actually starves the loop)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn
        # graftpulse: the loader is a worker-growth target for the
        # lens-driven autotuner (weak registration; a no-op path when
        # GRAFT_AUTOTUNE is off — the default)
        from ...telemetry import autotune as _autotune
        _autotune.register_loader(self)

    def set_num_workers(self, n):
        """Re-tune the worker count LIVE (the graftpulse autotuner's
        knob).  Growth takes effect mid-epoch: the pool's thread cap is
        raised in place and the open epoch iterator tops its lookahead
        up on the next batch — a synchronous (``num_workers=0``) open
        iterator switches to the pooled pipeline on its next batch;
        shrinking only lowers the target for the next epoch (running
        threads idle out — never torn down under an in-flight batch)."""
        n = max(0, int(n))
        self._num_workers = n
        pool = self._pool
        if pool is not None \
                and isinstance(getattr(pool, "_max_workers", None), int) \
                and n > pool._max_workers:
            # ThreadPoolExecutor spawns lazily up to _max_workers on
            # submit; raising the cap grows it without a restart.  The
            # attribute is stdlib-private — the getattr/type guard means
            # a CPython that renames it degrades to deeper lookahead on
            # the existing threads (full growth after close() rebuilds
            # the pool) instead of silently "growing" a dead attribute
            pool._max_workers = n

    def prefetch_depth(self):
        """Effective lookahead depth: the live per-loader override when
        one is set (``set_prefetch_depth``), else
        :func:`prefetch_depth_default`."""
        d = self._prefetch_depth
        return prefetch_depth_default() if d is None else d

    def set_prefetch_depth(self, n):
        """Re-tune the lookahead depth LIVE (the graftpulse autotuner's
        second data knob).  Like ``set_num_workers``, an open epoch
        iterator re-reads the depth on its next batch, so growth deepens
        the pipeline mid-epoch; shrinking drains naturally (in-flight
        futures complete, top-up just stops earlier)."""
        self._prefetch_depth = max(1, int(n))

    def _worker_pool(self):
        """The loader's thread pool, created on first use and REUSED
        across epochs — tearing a pool down and respawning its threads
        every ``__iter__`` (one per epoch) paid thread start-up latency
        exactly when the next epoch's first batches were needed."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, self._num_workers),
                thread_name_prefix="graft-dataloader")
        return self._pool

    def close(self):
        """Shut the worker pool down (idempotent; a later ``__iter__``
        lazily recreates it).  Do not call while an epoch iterator is
        mid-flight — its next lookahead submit would raise."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass                # interpreter teardown: nothing to save

    def __iter__(self):
        import time as _time
        from ...telemetry import lens as _lens
        prefetch = device_prefetch_enabled(self._prefetch_device)
        it = iter(self._batch_sampler)
        if self._num_workers == 0 and not prefetch:
            switched = False
            for batch in it:
                # synchronous batch production IS the consumer's wait:
                # the whole load+batchify lands on graftlens' data_wait
                t0 = _time.perf_counter()
                out = self._batchify_fn(
                    [self._dataset[idx] for idx in batch])
                t1 = _time.perf_counter()
                self._blocked_wait_s += t1 - t0
                _lens.io_wait(t0, t1)
                yield out
                if self._num_workers > 0:
                    # a live set_num_workers (the autotuner's grow)
                    # landed mid-epoch: without this re-check the open
                    # sync generator never consults the knob again —
                    # the controller would walk it to the cap on zero
                    # feedback.  Remaining batches flow through the
                    # pooled pipeline below
                    switched = True
                    break
            if not switched:
                return
        # thread-pool pipeline with one-batch lookahead (double
        # buffering); num_workers=0 + device prefetch runs the same
        # pipeline on ONE thread — batches stay sequential and ordered,
        # but batch N+1 builds (and its H2D issues) under batch N's
        # compute instead of under the consumer's wait
        pool = self._worker_pool()

        def make(batch):
            # graftarmor chaos site: a worker-thread batch build can be
            # delayed (slow disk) or failed (bad record) by GRAFT_FAULTS
            from ...armor import faults as _faults
            _faults.fault_point("dataloader.worker", n=len(batch))
            out = self._batchify_fn([self._dataset[idx] for idx in batch])
            if prefetch:
                # the lookahead batch's host→device transfer goes on the
                # wire NOW, from the worker thread (engine.offband keeps
                # any open bulk segment on this thread untouched)
                issue_device_prefetch(out)
            return out
        futures = []

        def top_up():
            # lookahead depth is re-read each batch so a live
            # set_num_workers / set_prefetch_depth (the autotuner's
            # grows) deepens the pipeline mid-epoch instead of waiting
            # for the next one
            want = max(self.prefetch_depth(), self._num_workers)
            try:
                while len(futures) < want:
                    futures.append(pool.submit(make, next(it)))
            except StopIteration:
                pass
        try:
            top_up()
            while futures:
                # only the blocked .result() counts as data_wait — a
                # lookahead batch that is already done costs ~0 here,
                # which is exactly the attribution the double-buffering
                # claim needs to be auditable
                t0 = _time.perf_counter()
                out = futures.pop(0).result()
                t1 = _time.perf_counter()
                self._blocked_wait_s += t1 - t0
                _lens.io_wait(t0, t1)
                top_up()
                yield out
        finally:
            # abandoned epoch (break / exception in the consumer): the
            # pool now outlives the iterator, so queued lookahead work
            # must not linger into the next epoch
            for f in futures:
                f.cancel()

    def __len__(self):
        return len(self._batch_sampler)
