"""DataLoader (ref: python/mxnet/gluon/data/dataloader.py).

The reference forks worker processes and ships batches through POSIX
shared-memory NDArrays (dataloader.py:72 rebuild_ndarray).  The TPU-native
pipeline keeps augmentation on host CPU in a thread pool — numpy transforms
release the GIL, jax.device_put overlaps H2D with compute — and hands the
device exactly one ready batch ahead (double-buffering, the same effect the
reference's prefetcher iterators achieve: src/io/iter_prefetcher.h).

graftduplex prefetch-to-device (GRAFT_PREFETCH_DEVICE, default on): each
lookahead batch's host→device transfer is ISSUED on the worker thread
under ``engine.offband()`` the moment the batch is built
(``io.issue_device_prefetch`` — the same issue/wait split ``ReduceHandle``
gave the gradient wire), so batch N+1's bytes stream to the device while
batch N computes.  With ``num_workers=0`` the loader now runs the same
one-batch-lookahead pipeline on a single pool thread (batches stay
sequential and in order — the reference's prefetcher iterators thread the
"synchronous" path the same way, iter_prefetcher.h); set
``GRAFT_PREFETCH_DEVICE=0`` or ``prefetch_device=False`` for the strictly
consumer-thread behavior.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...io import device_prefetch_enabled, issue_device_prefetch
from ...ndarray import NDArray
from ... import ndarray as _nd
from .sampler import BatchSampler, RandomSampler, SequentialSampler, Sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref: dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return _nd.ndarray.concatenate([d.expand_dims(0) for d in data], axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return _nd.array(data, dtype=data.dtype)


class DataLoader(object):
    """Loads batches from a Dataset (ref: dataloader.py class DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, prefetch_device=None):
        self._dataset = dataset
        self._prefetch_device = prefetch_device     # None = GRAFT_PREFETCH_DEVICE
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is "
                                 "specified")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = num_workers
        self._pool = None       # lazily-created per-loader worker pool
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def _worker_pool(self):
        """The loader's thread pool, created on first use and REUSED
        across epochs — tearing a pool down and respawning its threads
        every ``__iter__`` (one per epoch) paid thread start-up latency
        exactly when the next epoch's first batches were needed."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=max(1, self._num_workers),
                thread_name_prefix="graft-dataloader")
        return self._pool

    def close(self):
        """Shut the worker pool down (idempotent; a later ``__iter__``
        lazily recreates it).  Do not call while an epoch iterator is
        mid-flight — its next lookahead submit would raise."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass                # interpreter teardown: nothing to save

    def __iter__(self):
        import time as _time
        from ...telemetry import lens as _lens
        prefetch = device_prefetch_enabled(self._prefetch_device)
        if self._num_workers == 0 and not prefetch:
            for batch in self._batch_sampler:
                # synchronous batch production IS the consumer's wait:
                # the whole load+batchify lands on graftlens' data_wait
                t0 = _time.perf_counter()
                out = self._batchify_fn(
                    [self._dataset[idx] for idx in batch])
                _lens.io_wait(t0, _time.perf_counter())
                yield out
            return
        # thread-pool pipeline with one-batch lookahead (double
        # buffering); num_workers=0 + device prefetch runs the same
        # pipeline on ONE thread — batches stay sequential and ordered,
        # but batch N+1 builds (and its H2D issues) under batch N's
        # compute instead of under the consumer's wait
        pool = self._worker_pool()

        def make(batch):
            out = self._batchify_fn([self._dataset[idx] for idx in batch])
            if prefetch:
                # the lookahead batch's host→device transfer goes on the
                # wire NOW, from the worker thread (engine.offband keeps
                # any open bulk segment on this thread untouched)
                issue_device_prefetch(out)
            return out
        futures = []
        it = iter(self._batch_sampler)
        depth = max(2, self._num_workers)
        try:
            try:
                for _ in range(depth):
                    futures.append(pool.submit(make, next(it)))
            except StopIteration:
                pass
            while futures:
                # only the blocked .result() counts as data_wait — a
                # lookahead batch that is already done costs ~0 here,
                # which is exactly the attribution the double-buffering
                # claim needs to be auditable
                t0 = _time.perf_counter()
                out = futures.pop(0).result()
                _lens.io_wait(t0, _time.perf_counter())
                try:
                    futures.append(pool.submit(make, next(it)))
                except StopIteration:
                    pass
                yield out
        finally:
            # abandoned epoch (break / exception in the consumer): the
            # pool now outlives the iterator, so queued lookahead work
            # must not linger into the next epoch
            for f in futures:
                f.cancel()

    def __len__(self):
        return len(self._batch_sampler)
