"""graftstep — whole-step compiled training: fwd+bwd+fused update as ONE
donated XLA program.

The steady-state train step has so far dispatched as bucketed-eager
segments: the CachedOp forward (one jit), the tape walk's vjp programs,
``concat_ctx_sum`` flats, ``reduce_many``, and one fused optimizer
program per bucket.  This module hands the WHOLE step to XLA instead
(the paper's hybridization idea carried to its endpoint — see
arXiv:1810.09868 / arXiv:2301.13062 on what whole-program compilation
unlocks): the forward re-records into the same pure-jittable trace
``CachedOp`` compiles (``block.hybrid_forward_dispatch`` under shadow
params), ``jax.vjp`` supplies the fused backward seeded by
``autograd.head_seed`` (the exact ``loss.backward()`` convention), and
``optimizer.fused_formula_applier``'s per-bucket multi-tensor formulas
run inside the same program with the parameter/state buffers DONATED
(``jax.jit(..., donate_argnums=...)``) so XLA reuses the old weight
memory for the new weights — cross-op fusion plus zero double-buffering
that no amount of eager-side overlap can reach.

Topology::

    no kvstore   →  ONE program:   (params, states, inputs, rng, lr, wd,
                                    rescale) → (loss, aux, params', states')
    kvstore      →  program A:     (params, inputs, rng) → (loss, aux, flats)
                    reduce_many    — the existing wire, AT the boundary
                    program B:     (params, states, reduced, lr, wd,
                                    rescale) → (params', states')   [donated]

Cross-worker reduce stays at the program boundary (``KVStore.reduce_many``
on the per-bucket flats, labeled ``compiled_step``) — the same bytes, the
same reduction algebra, one collective bracket per step.

**Guards and fallback.**  Each compiled entry is keyed on (input
shapes/dtypes, param-set identity, per-param shape/dtype/grad_req,
optimizer signature, context count, kvstore identity, bucket target):
any guard miss runs the bit-identical bucketed-eager path — the same
``record → backward → Trainer.step`` triple the user would have written
— and re-traces lazily, so a static-shape loop shows ZERO retraces after
step 2 (step 1 falls back and builds, step 2 onward dispatches
compiled).  ``GRAFT_STEP_COMPILE=0`` is the kill-switch: every call runs
the eager triple.

**lr as operand.**  Unlike graftfuse's constant-baked programs,
lr/wd/rescale enter the compiled step as traced OPERANDS —
``set_learning_rate`` (and schedulers, and batch-size changes) must not
retrace a steady-state program.  Operands can shift LLVM's
fma-contraction choices by ~1 ULP vs the constant layout (measured on
bf16 mp_sgd), so compiled-vs-eager parity is asserted under a small
documented ULP tolerance (:func:`max_ulp_diff`, the EH104 convention)
rather than byte equality.

**Overlap semantics.**  Compiled-step mode DISABLES the mid-backward
reduce overlap (``BucketScheduler``) and the duplex pull overlap for its
own steps: there is no eager backward for grad-ready hooks to fire in —
the overlap the scheduler bought by hand is subsumed by XLA scheduling
inside the single program, and the boundary reduce issues immediately
after program A with no host work in between.  Fallback steps re-enter
``Trainer.step`` and keep their normal overlap behavior.

**Telemetry.**  A compiled step books a conservation-exact lens window:
the program dispatch is booked through ``lens.device_async`` (ONE device
span per program via the pulse reaper), host time lands on the
``fwd``/``kvstore``/``update`` phase spans, ``data_wait`` keeps flowing
from the DataLoader, and ``host_gap`` stays the residual — the six
components still sum exactly to the step wall.  The step journal and
lens record carry ``compiled=True``.  Because parameters are donated,
``graft_mem_peak_bytes`` no longer includes the transient
old-weights+new-weights double residency (docs/observability.md,
"Whole-step compilation").

Per-param gradient buffers are NOT materialized on compiled steps
(``param.grad()`` holds stale values): the gradients live only inside
the program.  Loops that read grads (clipping, logging) should run those
steps eagerly or read the compiled loss outputs instead.

``python -m incubator_mxnet_tpu.gluon.step_compile --selftest`` runs the
lint-tier check: trace → at most 2 guarded retraces → ULP-parity assert
against the bucketed-eager twin.
"""
from __future__ import annotations

import os
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import autograd
from .. import engine as _engine
from .. import optimizer as opt
from ..analysis import compile_safety as _csafety
from .. import random_state
from ..ndarray import NDArray
from ..telemetry import blackbox as _blackbox
from ..telemetry import lens as _lens
from ..telemetry import metrics as _tmetrics
from ..telemetry import tracing as _ttracing
from ..telemetry import xray as _xray
from .block import HybridBlock, _flatten, _regroup, _fmt_key, \
    _install_first_touch

__all__ = ["CompiledStep", "step_compile_enabled", "max_ulp_diff",
           "selftest", "main"]


def step_compile_enabled(override=None):
    """GRAFT_STEP_COMPILE (default on): whether :class:`CompiledStep`
    actually compiles.  Off = the kill-switch — every ``cstep(...)``
    call runs the bit-identical bucketed-eager triple instead, so a
    suspect compiled program can be ruled out without touching the
    training loop."""
    if override is not None:
        return bool(override)
    return os.environ.get("GRAFT_STEP_COMPILE", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def _donation_supported():
    """Buffer donation is honored on TPU/GPU; the CPU backend ignores it
    with a UserWarning per dispatch — skip the argnums there so the
    steady-state loop stays warning-free (the program is identical
    either way; only the aliasing hint differs)."""
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def max_ulp_diff(a, b):
    """Largest elementwise ULP distance between two equal-shape float
    arrays (inf on shape/dtype mismatch; 0/inf exact-compare for
    non-floats).  The EH104-style oracle the graftstep parity tests
    assert under: compiled programs pass lr/wd/rescale as traced
    operands where graftfuse bakes constants, which can shift
    fma-contraction by ~1 ULP per step."""
    a = np.asarray(jax.device_get(a))
    b = np.asarray(jax.device_get(b))
    if a.shape != b.shape or a.dtype != b.dtype:
        return float("inf")
    is_float = a.dtype.kind == "f" or a.dtype.name in ("bfloat16",)
    if not is_float:
        return 0.0 if np.array_equal(a, b) else float("inf")
    nbits = a.dtype.itemsize * 8
    ib = {16: np.int16, 32: np.int32, 64: np.int64}[nbits]
    ai = a.view(ib).astype(np.int64)
    bi = b.view(ib).astype(np.int64)
    # two's-complement int view → monotone key over the reals (the
    # classic radix trick; ±0.0 map to the same key)
    int_min = -(1 << (nbits - 1))
    ak = np.where(ai >= 0, ai, int_min - ai)
    bk = np.where(bi >= 0, bi, int_min - bi)
    if ak.size == 0:
        return 0.0
    return int(np.max(np.abs(ak - bk)))


class _Ineligible(object):
    """Permanent marker entry: this guard signature can never compile
    (multi-context, non-fused optimizer, store-side update, …) — every
    hit takes the eager fallback without re-deriving why."""

    __slots__ = ("reason",)

    def __init__(self, reason):
        self.reason = reason


class CompiledStep(object):
    """One training step — forward, backward, fused optimizer update —
    re-dispatched as a single donated XLA program (two, at a kvstore
    boundary).  Built via :meth:`Trainer.compile_step`; call it in place
    of the ``record → backward → step`` triple::

        cstep = trainer.compile_step(net, loss=loss_fn)
        for data, label in loader:
            out = cstep(data, label, batch_size=data.shape[0])

    With ``loss=None`` the block's output IS the head: backward seeds
    ones exactly as ``out.backward()`` would (``autograd.head_seed``).
    With a ``loss`` callable the LAST positional arg is the label and
    the head is ``loss(block(*args[:-1]), label)``.

    Counters: ``retraces`` (guard misses that built an entry — must stay
    at 1 on a static loop), ``compiled_steps``, ``fallback_steps``;
    ``forward_order`` is the recorded first-touch parameter order the
    trainer's pull scheduling reuses (graftduplex pull priority).
    """

    def __init__(self, trainer, block, loss=None, enabled=None):
        if not isinstance(block, HybridBlock):
            raise TypeError(
                "CompiledStep requires a HybridBlock (the compiled step "
                "rides the CachedOp functionalized trace); got %s"
                % type(block))
        self._trainer = trainer
        self._block = block
        self._loss = loss
        self._enabled_override = enabled
        self._entries = _engine.BoundedCache()
        self.retraces = 0
        self.compiled_steps = 0
        self.fallback_steps = 0
        self.forward_order = None
        # graftguard (GRAFT_COMPILE_CHECK): lazily-created runtime
        # auditor + the last guard key, diffed on every miss so EH301
        # can name exactly which component churned
        self._auditor = None
        self._last_guard_key = None

    # -- public -------------------------------------------------------------
    def enabled(self):
        return step_compile_enabled(self._enabled_override)

    def __call__(self, *args, batch_size=1):
        if autograd.is_recording():
            raise RuntimeError(
                "CompiledStep called inside autograd.record(): the "
                "compiled step IS the whole record/backward/step triple "
                "— call it outside any recording scope")
        args = tuple(a if isinstance(a, NDArray) else _as_nd(a)
                     for a in args)
        tr = self._trainer
        if not self.enabled():
            return self._fallback(args, batch_size, "disabled")
        if _csafety.refresh():
            if self._auditor is None:
                self._auditor = _csafety.StepAuditor("trainer")
            self._auditor.note_call()
        if not tr._kv_initialized:
            # first step: kvstore init + optimizer state creation ride
            # the eager path, then the trace builds lazily below
            return self._miss(args, batch_size, "first-step")
        key = self._guard_key(args)
        entry = self._entries.get(key)
        if entry is None:
            return self._miss(args, batch_size, "guard-miss")
        self._last_guard_key = key
        if isinstance(entry, _Ineligible):
            return self._fallback(args, batch_size, entry.reason)
        plan_sig = self._plan_sig()
        if plan_sig != entry["plan_sig"]:
            # the bucket plan moved under us (autotuned target, state
            # arity flip): treat as a guard miss and rebuild
            self._entries[key] = None
            return self._miss(args, batch_size, "plan-change")
        return self._dispatch(entry, args, batch_size)

    # -- fallback: the bit-identical bucketed-eager triple ------------------
    def _fallback(self, args, batch_size, reason):
        self.fallback_steps += 1
        _tmetrics.trainer_compiled_fallback(reason)
        block, loss = self._block, self._loss
        with autograd.record():
            if loss is not None:
                out = loss(block(*args[:-1]), args[-1])
            else:
                out = block(*args)
            heads, _fmt = _flatten(out, "output")
        autograd.backward(list(heads))
        self._trainer.step(batch_size)
        return out

    def _miss(self, args, batch_size, reason):
        out = self._fallback(args, batch_size, reason)
        # lazy re-trace AFTER the eager step: states now exist, the plan
        # is fresh, and the next hit on this signature dispatches
        # compiled — one fallback step per distinct signature
        key = self._guard_key(args)
        # every miss names WHICH guard component churned: the diff feeds
        # the always-on graft_step_retraces_total{reason} metric and the
        # blackbox, and (when GRAFT_COMPILE_CHECK is on) the EH301
        # retrace-storm detector
        if reason == "guard-miss":
            component, detail = _csafety.diff_guard_key(
                self._last_guard_key, key)
        else:
            component, detail = reason, None
        self._last_guard_key = key
        _tmetrics.step_retrace(component)
        _blackbox.record("step_compile", event="miss", reason=reason,
                         component=component, detail=detail)
        if self._auditor is not None and _csafety._ACTIVE[0]:
            self._auditor.note_miss(component, detail)
        try:
            if self._entries.get(key) is None:
                self._build(key, args)
        except Exception as e:   # never let trace failures kill training
            self._entries[key] = _Ineligible("trace-error")
            _blackbox.record("step_compile", event="ineligible",
                             reason="trace-error", error=repr(e))
        return out

    # -- guards -------------------------------------------------------------
    def _quant_cfg(self):
        """graftzero wire config for the compiled boundary: (mode, block)
        when the quantized bucket wire is on, else None.  Part of the
        guard key, so toggling ``GRAFT_QUANT_REDUCE`` re-traces exactly
        once — the encode/decode live INSIDE the donated programs."""
        tr = self._trainer
        kv = tr._kvstore_obj
        if kv is None:
            return None
        from ..parallel import quant as _quant
        mode = _quant.resolve_mode(getattr(kv, "_quant_override", None))
        if mode is None:
            return None
        return (mode, _quant.resolve_block())

    def _guard_key(self, args):
        tr = self._trainer
        o = tr._optimizer
        flat_args, in_fmt = _flatten(args, "input")
        kv = tr._kvstore_obj
        return (
            tuple(None if a is None else
                  (tuple(a.shape), str(a.dtype)) for a in flat_args),
            _fmt_key(in_fmt),
            tuple(id(p) for p in tr._params),          # param-set identity
            tuple((p.name,
                   None if p.shape is None else tuple(p.shape),
                   str(np.dtype(p.dtype)), p.grad_req)
                  for p in tr._params),
            (type(o), bool(o.multi_precision),
             getattr(o, "momentum", None), o.clip_gradient,
             getattr(o, "beta1", None), getattr(o, "beta2", None),
             getattr(o, "epsilon", None)),
            len(tr._contexts),
            None if kv is None else (type(kv).__name__,
                                     bool(tr._update_on_kvstore)),
            tr._bucket_target_bytes(),
            self._quant_cfg(),
        )

    def _plan_sig(self):
        """Structural signature of the trainer's CURRENT bucket plan —
        compared against the entry's so an autotuner bucket move or a
        state-arity flip re-traces instead of running a stale program."""
        plan = self._trainer._fused_plan()
        if plan is None:
            return None
        buckets, leftover = plan
        return (tuple((tuple(b.indices), b.kind, str(np.dtype(b.dtype)))
                      for b in buckets), tuple(leftover))

    # -- build --------------------------------------------------------------
    def _ineligible(self, key, reason):
        self._entries[key] = _Ineligible(reason)
        _blackbox.record("step_compile", event="ineligible", reason=reason)
        _tmetrics.step_guard_entries(len(self._entries))
        return None

    def _build(self, key, args):
        tr = self._trainer
        if len(tr._contexts) != 1:
            return self._ineligible(key, "multi-context")
        if tr._update_on_kvstore:
            return self._ineligible(key, "update-on-kvstore")
        plan = tr._fused_plan()
        if plan is None:
            return self._ineligible(key, "no-fused-plan")
        buckets, leftover = plan
        if leftover:
            return self._ineligible(key, "leftover-params")
        if any(p.grad_req == "add" for p in tr._params):
            # grad accumulation spans steps; a single fused program
            # cannot replicate the cross-step accumulate semantics
            return self._ineligible(key, "grad-req-add")
        block_params = self._block.collect_params()
        by_name = {p.name: i for i, p in enumerate(tr._params)}
        for name, bp in block_params.items():
            i = by_name.get(name)
            if i is not None and tr._params[i] is not bp:
                return self._ineligible(key, "param-identity-mismatch")

        trainable = tuple(i for b in buckets for i in b.indices)
        tpos = {i: k for k, i in enumerate(trainable)}
        train_names = tuple(tr._params[i].name for i in trainable)
        train_set = set(train_names)
        frozen_names = tuple(sorted(n for n in block_params
                                    if n not in train_set))
        updater = tr._updaters[0]
        bspecs = []
        for b in buckets:
            arrs0 = opt._fused_state_arrays(
                b.kind, updater.ensure_state(
                    b.indices[0], tr._params[b.indices[0]].list_data()[0]))
            arity = len(arrs0)
            has_state = arity >= (2 if b.kind == "mp_sgd" else 1)
            cfg = opt._fused_config(tr._optimizer, b.kind)
            shapes = tuple(tuple(tr._params[i].shape) for i in b.indices)
            bspecs.append({
                "indices": tuple(b.indices), "kind": b.kind,
                "arity": arity, "has_state": has_state,
                "shapes": shapes,
                # nests inside xray:update[k] at the call sites; the
                # hyphen spelling keeps it OUT of phase attribution
                # (which keys on "xray:" tokens) while the raw trace
                # still names the formula kind
                "apply": opt.fused_formula_applier(
                    b.kind, cfg, has_state,
                    scope="xray-apply-%s" % b.kind),
            })

        flat_args, in_fmt = _flatten(args, "input")
        entry = {
            "plan_sig": self._plan_sig(),
            "trainable": trainable, "tpos": tpos,
            "train_names": train_names, "frozen_names": frozen_names,
            "bspecs": bspecs, "in_fmt": in_fmt,
            "touch": [], "fmt_cell": {},
            "n_in": len(flat_args),
        }
        # graftguard EH303: the fused-config scalars baked into the
        # formula appliers at trace time, re-hashed per dispatch —
        # drift under an unchanged guard key means a silently frozen
        # value inside the compiled program
        entry["bake_kinds"] = tuple(s["kind"] for s in bspecs)
        entry["bake_sig"] = tuple(
            tuple(opt._fused_config(tr._optimizer, s["kind"]))
            for s in bspecs)

        raw_fwd = self._make_raw_fwd(entry)
        fwd_bwd = self._make_fwd_bwd(entry, raw_fwd)
        donate = (0, 1) if _donation_supported() else ()
        kv = tr._kvstore_obj
        # programs carry stable __name__s so the XLA module names
        # ("jit_gstep_one", …) are joinable against graftxray's program
        # registry and a profiler trace's hlo_module column
        entry["aot"] = {}
        if kv is None:
            one = self._make_one_program(entry, fwd_bwd)
            one.__name__ = "gstep_one"
            entry["one"] = jax.jit(one, donate_argnums=donate)
            entry["fwd_bwd"] = entry["update"] = None
            # un-jitted twin for the EH304 divergence sentinel: same
            # closure, eager dispatch — zero cost unless sampled
            entry["one_raw"] = one
            entry["fwd_bwd_raw"] = entry["update_raw"] = None
        else:
            update = self._make_update_program(entry)
            update.__name__ = "gstep_update"
            entry["one"] = None
            entry["one_raw"] = None
            qcfg = self._quant_cfg()
            entry["quant"] = qcfg
            if qcfg is None:
                def gstep_fwd_bwd(tv, fv, iv, rng):
                    return fwd_bwd(tv, fv, iv, rng, True)

                entry["fwd_bwd"] = jax.jit(gstep_fwd_bwd)
                entry["update"] = jax.jit(update, donate_argnums=donate)
                entry["fwd_bwd_raw"] = gstep_fwd_bwd
                entry["update_raw"] = update
            else:
                # graftzero: the quantize (error-feedback encode) and
                # dequantize live INSIDE the donated programs — the host
                # boundary ships only packed codes + per-block scales
                # (kv.reduce_quantized).  Residuals ride as operands and
                # outputs of program A, stored back in the Updater store
                # under the same keys the eager BucketQuantizer uses, so
                # eager and compiled steps share one EF trajectory.
                from ..parallel import quant as _quant
                mode, qblock = qcfg
                sizes = tuple(
                    int(sum(int(np.prod(s)) if s else 1
                            for s in spec["shapes"]))
                    for spec in bspecs)
                qdtypes = tuple(
                    np.dtype(tr._params[spec["indices"][0]].dtype)
                    for spec in bspecs)
                entry["qsizes"] = sizes
                entry["qdtypes"] = qdtypes

                def gstep_fwd_bwd_q(tv, fv, iv, rng, res):
                    outs, aux, flats = fwd_bwd(tv, fv, iv, rng, True)
                    codes, scales, new_res = [], [], []
                    for k, f in enumerate(flats):
                        with jax.named_scope("xray:quant[%d]" % k):
                            acc = f.astype(jnp.float32) + res[k]
                            c, s = _quant.encode(acc, mode, qblock)
                            codes.append(c)
                            scales.append(s)
                            new_res.append(acc - _quant.decode(
                                c, s, sizes[k], mode, qblock))
                    return (outs, aux, tuple(codes), tuple(scales),
                            tuple(new_res))

                def gstep_update_q(train_vals, state_vals, payloads,
                                   lrs, wds, rescale):
                    flats = []
                    for k in range(len(sizes)):
                        with jax.named_scope("xray:dequant[%d]" % k):
                            c, s = payloads[k]
                            flats.append(_quant.decode(
                                c, s, sizes[k], mode,
                                qblock).astype(qdtypes[k]))
                    return update(train_vals, state_vals, tuple(flats),
                                  lrs, wds, rescale)

                gstep_fwd_bwd_q.__name__ = "gstep_fwd_bwd_q"
                gstep_update_q.__name__ = "gstep_update_q"
                entry["fwd_bwd"] = jax.jit(gstep_fwd_bwd_q)
                entry["update"] = jax.jit(gstep_update_q,
                                          donate_argnums=donate)
                entry["fwd_bwd_raw"] = gstep_fwd_bwd_q
                entry["update_raw"] = gstep_update_q

        # dry abstract trace NOW (jax.eval_shape: no compile, no FLOPs):
        # trace errors surface here as a clean ineligible entry instead
        # of mid-loop, the output fmt lands in fmt_cell, and the shadow
        # first-touch hooks record the forward-use order
        avals = self._avals(entry, args)
        try:
            jax.eval_shape(lambda tv, fv, iv, rng:
                           fwd_bwd(tv, fv, iv, rng, kv is not None), *avals)
        except Exception as e:
            return self._ineligible(key, "trace-error: %s" % type(e).__name__)
        self._feed_first_touch(entry)
        self._entries[key] = entry
        self.retraces += 1
        _tmetrics.trainer_compiled_retrace()
        _tmetrics.step_guard_entries(len(self._entries))
        _blackbox.record("step_compile", event="trace",
                         n_params=len(trainable), n_buckets=len(bspecs),
                         kv=kv is not None, donated=bool(donate),
                         retraces=self.retraces)
        return entry

    def _avals(self, entry, args):
        tr = self._trainer
        flat_args, _ = _flatten(args, "input")

        def av(x):
            return jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype))

        tv = tuple(av(tr._params[i].list_data()[0]._read())
                   for i in entry["trainable"])
        block_params = self._block.collect_params()
        fv = tuple(av(block_params[n].list_data()[0]._read())
                   for n in entry["frozen_names"])
        iv = tuple(None if a is None else av(a._read()) for a in flat_args)
        rng = av(random_state.next_key())
        return tv, fv, iv, rng

    def _feed_first_touch(self, entry):
        """graftduplex pull priority: the forward-use order recorded by
        the trace shadows becomes the trainer's first-touch order — the
        PullScheduler issues weight pulls in the order the next forward
        will consume them, and ``GRAFT_BUCKET_ORDER=touch`` packs
        buckets by it."""
        tr = self._trainer
        by_name = {p.name: i for i, p in enumerate(tr._params)}
        order = tuple(by_name[n] for n in entry["touch"] if n in by_name)
        if order:
            self.forward_order = order
            tr.note_first_touch_order(order)

    # -- traced pieces ------------------------------------------------------
    def _make_raw_fwd(self, entry):
        block, loss = self._block, self._loss
        train_names = entry["train_names"]
        frozen_names = entry["frozen_names"]
        in_fmt = entry["in_fmt"]
        touch = entry["touch"]
        fmt_cell = entry["fmt_cell"]

        def raw_fwd(train_vals, frozen_vals, input_vals, rng):
            shadows = {}
            for n, v in zip(train_names, train_vals):
                shadows[n] = NDArray(v)
            for n, v in zip(frozen_names, frozen_vals):
                shadows[n] = NDArray(v)
            if not touch:
                _install_first_touch(shadows, touch)
            nd_in = [None if v is None else NDArray(v) for v in input_vals]
            if loss is not None:
                label_nd, nd_in = nd_in[-1], nd_in[:-1]
            args, _ = _regroup(nd_in, in_fmt if loss is None
                               else in_fmt[:-1] if isinstance(in_fmt, list)
                               else in_fmt)
            if not isinstance(args, list):
                args = [args]
            # graftxray phase marker: every op staged by the forward
            # (and therefore its vjp RESIDUALS' producers) carries
            # "xray:forward" in its HLO op_name metadata — the profiler
            # attribution joins on it (telemetry/xray.py)
            with jax.named_scope("xray:forward"):
                with random_state.use_key(rng):
                    with autograd._scope(recording=False, training=True):
                        with block._trace_params(shadows):
                            out = block.hybrid_forward_dispatch(*args)
                            if loss is not None:
                                out = loss(out, label_nd)
            flat_out, fmt = _flatten(out, "output")
            # graftlint: disable=GL304 -- trace-time output-fmt memo, written once per trace
            fmt_cell["fmt"] = fmt
            out_vals = tuple(o._read() for o in flat_out)
            for n in train_names:
                if shadows[n]._version > 0:
                    raise RuntimeError(
                        "trainable parameter %r mutated inside the "
                        "forward trace — unsupported in a compiled step "
                        "(the optimizer update owns that buffer)" % n)
            aux = {n: shadows[n]._read() for n in frozen_names
                   if shadows[n]._version > 0}
            return out_vals, aux

        return raw_fwd

    def _make_fwd_bwd(self, entry, raw_fwd):
        bspecs = entry["bspecs"]
        tpos = entry["tpos"]

        def fwd_bwd(train_vals, frozen_vals, input_vals, rng, flat_mode):
            outs, vjp_fn, aux = jax.vjp(
                lambda tv: raw_fwd(tv, frozen_vals, input_vals, rng),
                tuple(train_vals), has_aux=True)
            # graftxray: ops staged by the vjp application (the whole
            # backward sweep + head seeding + flat packing) are tagged
            # "xray:backward"; the vjp's forward ops already carry
            # "xray:forward" from raw_fwd
            with jax.named_scope("xray:backward"):
                # seed exactly as loss.backward() seeds a bare head
                cts = tuple(autograd.head_seed(o) for o in outs)
                (grads,) = vjp_fn(cts)
                if not flat_mode:
                    return outs, aux, grads
                flats = tuple(
                    _engine.flatten_arrays(
                        tuple(grads[tpos[i]] for i in spec["indices"]))
                    for spec in bspecs)
            return outs, aux, flats

        return fwd_bwd

    def _make_one_program(self, entry, fwd_bwd):
        """No-kvstore topology: fwd+bwd+update in ONE jitted program, the
        per-param-gradient formula layout (flat_mode=False) the eager
        storeless ``_bucketed_update`` uses — same math, one dispatch."""
        bspecs = entry["bspecs"]
        tpos = entry["tpos"]

        def one(train_vals, state_vals, frozen_vals, input_vals, rng,
                lrs, wds, rescale):
            outs, aux, grads = fwd_bwd(train_vals, frozen_vals,
                                       input_vals, rng, False)
            new_w = list(train_vals)
            new_s = []
            for k, spec in enumerate(bspecs):
                with jax.named_scope("xray:update[%d]" % k):
                    ws = tuple(train_vals[tpos[i]] for i in spec["indices"])
                    gs = tuple(grads[tpos[i]] for i in spec["indices"])
                    nw, ns = spec["apply"](ws, gs, state_vals[k],
                                           lrs[k], wds[k], rescale)
                for pos, i in enumerate(spec["indices"]):
                    new_w[tpos[i]] = nw[pos]
                new_s.append(ns)
            return outs, aux, tuple(new_w), tuple(new_s)

        return one

    def _make_update_program(self, entry):
        """Kvstore topology, program B: unflatten each bucket's REDUCED
        flat (the same static slicing the graftfuse flat_mode programs
        inline) and apply the per-bucket formulas — params/states
        donated, so XLA aliases the old weight buffers for the new."""
        bspecs = entry["bspecs"]
        tpos = entry["tpos"]

        def update(train_vals, state_vals, flats, lrs, wds, rescale):
            new_w = list(train_vals)
            new_s = []
            for k, spec in enumerate(bspecs):
                with jax.named_scope("xray:update[%d]" % k):
                    ws = tuple(train_vals[tpos[i]] for i in spec["indices"])
                    gs = _engine.unflatten(flats[k], spec["shapes"])
                    nw, ns = spec["apply"](ws, gs, state_vals[k],
                                           lrs[k], wds[k], rescale)
                for pos, i in enumerate(spec["indices"]):
                    new_w[tpos[i]] = nw[pos]
                new_s.append(ns)
            return tuple(new_w), tuple(new_s)

        return update

    # -- dispatch -----------------------------------------------------------
    def _gather(self, entry, args):
        tr = self._trainer
        flat_args, _ = _flatten(args, "input")
        if _engine.in_bulk():
            # land any open deferred segment ONCE with an attributed
            # cause (param/state leaves may be deferred values)
            _engine.flush(cause="step_compile")
        train_nds = [tr._params[i].list_data()[0]
                     for i in entry["trainable"]]
        train_vals = tuple(a._read() for a in train_nds)
        block_params = self._block.collect_params()
        frozen_nds = [block_params[n].list_data()[0]
                      for n in entry["frozen_names"]]
        frozen_vals = tuple(a._read() for a in frozen_nds)
        input_vals = tuple(None if a is None else a._read()
                           for a in flat_args)
        updater = tr._updaters[0]
        state_nds, state_vals = [], []
        for spec in entry["bspecs"]:
            nds = []
            for i in spec["indices"]:
                arrs = opt._fused_state_arrays(
                    spec["kind"], updater.ensure_state(
                        i, tr._params[i].list_data()[0]))
                if len(arrs) != spec["arity"]:
                    return None     # state store moved: caller falls back
                nds.append(arrs)
            state_nds.append(nds)
            state_vals.append(tuple(tuple(a._read() for a in arrs)
                                    for arrs in nds))
        return (train_vals, frozen_vals, input_vals, frozen_nds,
                state_nds, tuple(state_vals), train_nds)

    def _gather_residuals(self, entry):
        """graftzero EF operands: one f32 residual per bucket, read from
        (and later written back to) the Updater store under the SAME
        keys the eager BucketQuantizer uses — eager and compiled steps
        share one error-feedback trajectory, and checkpoint/resume
        carries it."""
        from ..parallel import quant as _quant
        updater = self._trainer._updaters[0]
        keys, vals = [], []
        for k, spec in enumerate(entry["bspecs"]):
            key = _quant.residual_key(spec["indices"],
                                      entry["qdtypes"][k])
            r = updater.states.get(key)
            if r is None:
                r = jnp.zeros((entry["qsizes"][k],), jnp.float32)
            elif not isinstance(r, jnp.ndarray):
                # set_states round trip parks residuals as host numpy
                r = jnp.asarray(np.asarray(r), dtype=jnp.float32)
            keys.append(key)
            vals.append(r)
        return keys, tuple(vals)

    def _aot(self, entry, kind, cargs):
        """Resolve the executable for program ``kind`` ("one",
        "fwd_bwd", "update").  The first dispatch AOT-lowers and
        compiles the jit wrapper (``.lower(*args).compile()``) — the
        same trace+compile the first jit call would have paid, done
        explicitly so the :class:`jax.stages.Compiled` handle exists:
        graftxray reads its HLO text (phase scope maps) and
        cost/memory analysis (``xray.note_program`` → blackbox
        ``xray_cost`` / retrace ``xray_cost_diff`` journals).  lr/wd/
        rescale ride as weak-typed scalar OPERANDS, so later calls with
        different values reuse the same executable (probed; the
        selftest's set_learning_rate leg asserts it).  Any AOT failure
        pins the plain jit wrapper instead — dispatch never breaks for
        want of introspection."""
        c = entry["aot"].get(kind)
        if c is None:
            jfn = entry[kind]
            try:
                c = jfn.lower(*cargs).compile()
                _xray.note_program(
                    "gstep_" + kind, c,
                    label="%s/%dp/%db" % (kind, len(entry["trainable"]),
                                          len(entry["bspecs"])))
            except Exception:
                c = jfn
            entry["aot"][kind] = c
        return c

    def _dispatch(self, entry, args, batch_size):
        tr = self._trainer
        optimizer = tr._optimizer
        optimizer.rescale_grad = tr._scale / batch_size
        gathered = self._gather(entry, args)
        if gathered is None:
            return self._miss(args, batch_size, "state-arity")
        (train_vals, frozen_vals, input_vals, frozen_nds,
         state_nds, state_vals, train_nds) = gathered
        # host bookkeeping ticks in the exact _bucketed_update order
        # (bucket outer, param inner) — update counts, schedulers and
        # Adam's bias correction see the same sequence as eager; the
        # resolved scalars then ride as traced OPERANDS (no retrace on
        # set_learning_rate / wd / batch-size changes)
        lrs, wds = [], []
        for spec in entry["bspecs"]:
            lr_b, wd_b = [], []
            for i in spec["indices"]:
                lr, wd = opt.fused_lr_wd(optimizer, i, spec["kind"])
                lr_b.append(lr)
                wd_b.append(wd)
            lrs.append(tuple(lr_b))
            wds.append(tuple(wd_b))
        lrs, wds = tuple(lrs), tuple(wds)
        rescale = float(optimizer.rescale_grad)
        rng = random_state.next_key()
        kv = tr._kvstore_obj
        ctx = tr._contexts[0]

        # graftguard (GRAFT_COMPILE_CHECK): EH303 re-hashes the fused
        # config against the trace-time bake, EH302 poisons the donated
        # buffers for the dispatch window, EH304 replays the un-jitted
        # twin on sampled steps (same operands, same rng key)
        aud = self._auditor if _csafety._ACTIVE[0] else None
        sentinel = deep = False
        if aud is not None:
            deep = aud.deep_due()
            if deep:
                aud.check_bake(
                    entry["bake_kinds"], entry["bake_sig"],
                    tuple(tuple(opt._fused_config(optimizer, k))
                          for k in entry["bake_kinds"]))
            sentinel = aud.sentinel_due()

        # graftxray capture window: one memoized env read when idle;
        # when a session is due (pending trigger / GRAFT_XRAY_EVERY) it
        # brackets the next GRAFT_XRAY_STEPS dispatches with
        # jax.profiler and attributes device ops to the xray:* phases
        new_w = None
        _xray.dispatch_begin()
        try:
            with _blackbox.step_journal("trainer", batch_size=batch_size,
                                        fused=True, overlapped=False,
                                        duplex=False, compiled=True):
                with _ttracing.phase_span("kvstore"):
                    # settle any in-flight pulls from a preceding
                    # fallback step; compiled steps never arm the
                    # mid-backward scheduler (no eager backward → no
                    # grad-ready hooks)
                    tr._pull_scheduler.finish()
                    if tr._scheduler._armed:
                        tr._scheduler.disarm()
                with _engine.offband():
                    if kv is None:
                        with _ttracing.phase_span("update"):
                            ref = None
                            if sentinel:
                                ref = entry["one_raw"](
                                    train_vals, state_vals, frozen_vals,
                                    input_vals, rng, lrs, wds, rescale)
                            if deep:
                                aud.poison(_donated_nds(train_nds,
                                                        state_nds),
                                           "one")
                            cargs = (train_vals, state_vals, frozen_vals,
                                     input_vals, rng, lrs, wds, rescale)
                            one_c = self._aot(entry, "one", cargs)
                            t0 = time.perf_counter()
                            outs, aux, new_w, new_s = one_c(*cargs)
                            _lens.device_async(
                                [new_w[-1] if new_w else outs[0]], t0)
                            if ref is not None:
                                aud.check_parity(
                                    "one", (outs, aux, new_w, new_s),
                                    ref)
                            self._write_back(entry, new_w, new_s,
                                             state_nds, frozen_nds, aux)
                    else:
                        qcfg = entry.get("quant")
                        with _ttracing.phase_span("fwd"):
                            if qcfg is None:
                                cargs = (train_vals, frozen_vals,
                                         input_vals, rng)
                            else:
                                res_keys, res_vals = \
                                    self._gather_residuals(entry)
                                cargs = (train_vals, frozen_vals,
                                         input_vals, rng, res_vals)
                            fb_c = self._aot(entry, "fwd_bwd", cargs)
                            t0 = time.perf_counter()
                            fb_out = fb_c(*cargs)
                            if qcfg is None:
                                outs, aux, flats = fb_out
                                _lens.device_async([flats[-1]], t0)
                            else:
                                (outs, aux, qcodes, qscales,
                                 new_res) = fb_out
                                _lens.device_async([qscales[-1]], t0)
                                # EF residual write-back NOW — it is
                                # this step's local quantization error,
                                # independent of the wire reduce; same
                                # store keys as the eager quantizer
                                updater = tr._updaters[0]
                                for rk, r in zip(res_keys, new_res):
                                    updater.states[rk] = r
                        with _ttracing.phase_span("kvstore"):
                            # cross-worker reduce AT the program
                            # boundary: the existing wire, same bytes,
                            # same algebra — or (graftzero) the packed
                            # quantized payload, ONE collective batch
                            if qcfg is None:
                                flat_nds = [NDArray(f, ctx=ctx)
                                            for f in flats]
                                kv.reduce_many(flat_nds,
                                               label="compiled_step")
                                reduced = tuple(f._read()
                                                for f in flat_nds)
                            else:
                                mode, qblock = qcfg
                                pairs = [(NDArray(c, ctx=ctx),
                                          NDArray(s, ctx=ctx))
                                         for c, s in zip(qcodes,
                                                         qscales)]
                                kv.reduce_quantized(
                                    pairs, list(entry["qsizes"]),
                                    mode, qblock,
                                    label="compiled_step")
                                reduced = tuple(
                                    (c._read(), s._read())
                                    for c, s in pairs)
                        with _ttracing.phase_span("update"):
                            ref_u = None
                            if sentinel:
                                aud.check_parity(
                                    "fwd_bwd", fb_out,
                                    entry["fwd_bwd_raw"](*cargs))
                                ref_u = entry["update_raw"](
                                    train_vals, state_vals, reduced,
                                    lrs, wds, rescale)
                            if deep:
                                aud.poison(_donated_nds(train_nds,
                                                        state_nds),
                                           "update")
                            cargs = (train_vals, state_vals, reduced,
                                     lrs, wds, rescale)
                            up_c = self._aot(entry, "update", cargs)
                            t1 = time.perf_counter()
                            new_w, new_s = up_c(*cargs)
                            _lens.device_async(
                                [new_w[-1] if new_w else reduced[-1]],
                                t1)
                            if ref_u is not None:
                                aud.check_parity("update",
                                                 (new_w, new_s), ref_u)
                            self._write_back(entry, new_w, new_s,
                                             state_nds, frozen_nds, aux)
                    _lens.mem_sample("compiled_step")
        finally:
            if aud is not None:
                aud.sweep()
            # closes an open capture session once it spans
            # GRAFT_XRAY_STEPS dispatches (blocks on the new weights so
            # the device work lands inside the trace); one env read when
            # idle, and an errored dispatch still counts so a session
            # can't be left open across an exception
            _xray.dispatch_end(sync=new_w)
        self.compiled_steps += 1
        _tmetrics.trainer_compiled_step(len(entry["trainable"]))
        out_arrays = [NDArray(v, ctx=ctx) for v in outs]
        out, _ = _regroup(out_arrays, entry["fmt_cell"].get(
            "fmt", ["0"] * len(out_arrays)))
        return out

    def _write_back(self, entry, new_w, new_s, state_nds, frozen_nds, aux):
        tr = self._trainer
        tpos = entry["tpos"]
        for k, spec in enumerate(entry["bspecs"]):
            for pos, i in enumerate(spec["indices"]):
                tr._params[i].list_data()[0]._write(new_w[tpos[i]])
                for arr, val in zip(state_nds[k][pos], new_s[k][pos]):
                    arr._write(val)
        if aux:
            for n, nd in zip(entry["frozen_names"], frozen_nds):
                if n in aux:
                    nd._write(aux[n])

def _donated_nds(train_nds, state_nds):
    """The NDArrays whose buffers a dispatch donates (program positions
    0/1: train_vals + state_vals).  Poisoned by contract even where
    ``_donation_supported()`` is False — CPU CI must catch the
    read-after-donate that only real TPUs would corrupt.  Takes the
    arrays _gather already resolved (re-walking the param store per
    dispatch was measurable against the < 2% auditor budget)."""
    nds = list(train_nds)
    for bucket in state_nds:
        for arrs in bucket:
            nds.extend(arrs)
    return nds


def _as_nd(a):
    from .. import ndarray as _nd
    return _nd.array(np.asarray(a))


# ---------------------------------------------------------------------------
# selftest: trace → ≤2 guarded retraces → ULP-parity assert (lint tier)
# ---------------------------------------------------------------------------

# operand-vs-constant scalar layout can shift fma contraction ~1 ULP per
# step; a handful of steps compound to a few ULP.  EH104 convention.
SELFTEST_ULP_TOL = 8


def _make_net(prefix, n_params=4, shape=(1, 5)):
    from . import nn  # noqa: F401  (package side effects)

    class _Net(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                for k in range(n_params):
                    setattr(self, "w%d" % k,
                            self.params.get("w%d" % k, shape=shape))

        def hybrid_forward(self, F, x, **ps):
            acc = None
            for k in range(n_params):
                y = (ps["w%d" % k] * ps["w%d" % k] * x).sum()
                acc = y if acc is None else acc + y
            return acc

    return _Net(prefix=prefix)


def _seed_params(net, seed=7):
    import incubator_mxnet_tpu as mx
    rng = np.random.RandomState(seed)
    net.initialize(ctx=mx.cpu())
    for name in sorted(net.collect_params()):
        p = net.collect_params()[name]
        p.set_data(mx.nd.array(
            rng.uniform(-1, 1, p.shape).astype(np.float32)))


def selftest(verbose=False):
    """Returns a list of problems — empty means pass.  Exercises: lazy
    trace on step 1, compiled dispatch with ZERO retraces after step 2,
    one guarded retrace on a shape change (≤2 total), no retrace on
    set_learning_rate, and params+states ULP-parity vs the
    bucketed-eager twin throughout."""
    import incubator_mxnet_tpu as mx
    from . import Trainer

    problems = []
    net_e = _make_net("graftstep_e_")
    net_c = _make_net("graftstep_c_")
    _seed_params(net_e)
    _seed_params(net_c)
    tr_e = Trainer(net_e.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
                   kvstore=None)
    tr_c = Trainer(net_c.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
                   kvstore=None)
    cstep = CompiledStep(tr_c, net_c, enabled=True)

    def eager_step(x):
        with autograd.record():
            out = net_e(x)
        out.backward()
        tr_e.step(1)
        return out

    def parity(tag):
        names = sorted(net_e.collect_params())
        for ne, nc in zip(names, sorted(net_c.collect_params())):
            a = net_e.collect_params()[ne].data()._read()
            b = net_c.collect_params()[nc].data()._read()
            ulp = max_ulp_diff(a, b)
            if ulp > SELFTEST_ULP_TOL:
                problems.append("%s: weight %s diverged by %s ULP"
                                % (tag, ne, ulp))
        se, sc = tr_e._updaters[0].states, tr_c._updaters[0].states
        for i in se:
            for ae, ac in zip(opt._fused_state_arrays("sgd", se[i]),
                              opt._fused_state_arrays("sgd", sc[i])):
                ulp = max_ulp_diff(ae._read(), ac._read())
                if ulp > SELFTEST_ULP_TOL:
                    problems.append("%s: state[%d] diverged by %s ULP"
                                    % (tag, i, ulp))

    rngx = np.random.RandomState(3)
    for step in range(6):
        x = mx.nd.array(rngx.uniform(0.5, 1.5, (6, 5)).astype(np.float32))
        eager_step(x)
        cstep(x)
        if verbose:
            print("step %d retraces=%d compiled=%d fallback=%d"
                  % (step, cstep.retraces, cstep.compiled_steps,
                     cstep.fallback_steps))
    parity("static-loop")
    if cstep.retraces != 1:
        problems.append("static loop traced %d times (want exactly 1 — "
                        "zero retraces after step 2)" % cstep.retraces)
    if cstep.compiled_steps != 5:
        problems.append("expected 5 compiled dispatches after the lazy "
                        "step-1 trace, got %d" % cstep.compiled_steps)
    # lr change must NOT retrace (lr is a traced operand)
    tr_e.set_learning_rate(0.01)
    tr_c.set_learning_rate(0.01)
    x = mx.nd.array(rngx.uniform(0.5, 1.5, (6, 5)).astype(np.float32))
    eager_step(x)
    cstep(x)
    if cstep.retraces != 1:
        problems.append("set_learning_rate retraced the compiled step "
                        "(lr must ride as an operand)")
    parity("post-lr-change")
    # shape change: ONE guarded retrace (≤ 2 total), then compiled again
    for _ in range(2):
        x2 = mx.nd.array(rngx.uniform(0.5, 1.5, (3, 5)).astype(np.float32))
        eager_step(x2)
        cstep(x2)
    if cstep.retraces != 2:
        problems.append("shape change cost %d retraces (want exactly 2 "
                        "entries total)" % cstep.retraces)
    parity("post-shape-change")
    if cstep.forward_order is None:
        problems.append("first-touch forward order was not recorded by "
                        "the step trace")
    return problems


def main(argv=None):
    import argparse
    import sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(
        prog="python -m incubator_mxnet_tpu.gluon.step_compile",
        description="graftstep whole-step compilation selftest")
    ap.add_argument("--selftest", action="store_true",
                    help="trace → ≤2 guarded retraces → ULP-parity "
                         "assert vs the bucketed-eager twin (CI tier)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    if not args.selftest:
        ap.print_help()
        return 2
    problems = selftest(verbose=args.verbose)
    if problems:
        for p in problems:
            print("graftstep selftest FAIL: %s" % p, file=sys.stderr)
        return 1
    print("graftstep selftest OK (1 lazy trace, 0 steady-state retraces, "
          "1 guarded retrace on shape change, ULP parity held)")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
