"""Gluon fused RNN layers (ref: python/mxnet/gluon/rnn/rnn_layer.py).

RNN / LSTM / GRU over whole sequences via the fused RNN op (ops/rnn.py —
the lax.scan kernel standing in for cudnnRNNForwardTraining).  Parameter
naming matches the reference exactly ({l|r}{i}_{i2h|h2h}_{weight|bias}) so
checkpoints interconvert.
"""
from __future__ import annotations

from ..block import HybridBlock
from ... import initializer
from ...ndarray import NDArray
from ... import ndarray as _nd

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Base fused layer (ref: rnn_layer.py class _RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, i2h_weight_initializer, h2h_weight_initializer,
                 i2h_bias_initializer, h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer

        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        self._param_order = []
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                name = "%s%d_i2h_weight" % (j, i)
                p = self.params.get(name, shape=(ng * nh, ni),
                                    init=i2h_weight_initializer,
                                    allow_deferred_init=True)
                setattr(self, name, p)
                name = "%s%d_h2h_weight" % (j, i)
                p = self.params.get(name, shape=(ng * nh, nh),
                                    init=h2h_weight_initializer,
                                    allow_deferred_init=True)
                setattr(self, name, p)
                name = "%s%d_i2h_bias" % (j, i)
                p = self.params.get(name, shape=(ng * nh,),
                                    init=initializer.create(i2h_bias_initializer)
                                    if isinstance(i2h_bias_initializer, str)
                                    else i2h_bias_initializer,
                                    allow_deferred_init=True)
                setattr(self, name, p)
                name = "%s%d_h2h_bias" % (j, i)
                p = self.params.get(name, shape=(ng * nh,),
                                    init=initializer.create(h2h_bias_initializer)
                                    if isinstance(h2h_bias_initializer, str)
                                    else h2h_bias_initializer,
                                    allow_deferred_init=True)
                setattr(self, name, p)
            ni = nh * self._dir

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _pre_infer(self, x, *states):
        ni = x.shape[-1]
        nh, ng = self._hidden_size, self._gates
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                w = getattr(self, "%s%d_i2h_weight" % (j, i))
                if w.shape[1] == 0:
                    w.shape = (ng * nh, ni)
            ni = nh * self._dir

    def begin_state(self, batch_size=0, func=_nd.zeros, **kwargs):
        """Initial recurrent states (ref: rnn_layer.py begin_state)."""
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name="%sh0_%d" % (self.prefix, i), **info))
        return states

    def __call__(self, inputs, *states):
        if not states or states[0] is None:
            skip_states = True
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch, ctx=inputs.context)
        else:
            if isinstance(states[0], (list, tuple)):
                states = states[0]
            skip_states = False
        out = super().__call__(inputs, list(states))
        if skip_states:
            return out[0] if isinstance(out, (list, tuple)) else out
        return out

    def hybrid_forward(self, F, inputs, states, **params):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        arrays = [inputs] + list(states)
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                arrays.append(params["%s%d_i2h_weight" % (j, i)])
                arrays.append(params["%s%d_h2h_weight" % (j, i)])
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                arrays.append(params["%s%d_i2h_bias" % (j, i)])
                arrays.append(params["%s%d_h2h_bias" % (j, i)])
        out = F.RNN(*arrays, state_size=self._hidden_size,
                    num_layers=self._num_layers,
                    bidirectional=self._dir == 2, mode=self._mode,
                    p=self._dropout, state_outputs=True)
        outputs, hy, cy = out
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, dim1=0, dim2=1)
        if self._mode == "lstm":
            return outputs, [hy, cy]
        return outputs, [hy]


class RNN(_RNNLayer):
    """Multi-layer Elman RNN (ref: rnn_layer.py class RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (ref: rnn_layer.py class LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (ref: rnn_layer.py class GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
