"""Gluon recurrent cells (ref: python/mxnet/gluon/rnn/rnn_cell.py).

Same cell zoo as the reference: RNNCell, LSTMCell, GRUCell,
SequentialRNNCell, DropoutCell, ZoneoutCell, ResidualCell,
BidirectionalCell, with begin_state/unroll.  Gate slicing orders match the
fused RNN op (ops/rnn.py) exactly, as in the reference.
"""
from __future__ import annotations

from ..block import Block, HybridBlock
from ... import initializer
from ... import ndarray as _nd

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ZoneoutCell",
           "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(func=_nd.zeros, batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """ref: rnn_cell.py _format_sequence — normalize to list or tensor."""
    from ...ndarray import NDArray
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout else axis
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[in_axis]
            inputs = [x.reshape([y for i, y in enumerate(inputs.shape) if i != in_axis])
                      for x in _split_axis(inputs, inputs.shape[in_axis], in_axis)]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = [i.expand_dims(axis) for i in inputs]
            inputs = _nd.ndarray.concatenate(inputs, axis=axis)
            in_axis = axis
    if isinstance(inputs, NDArray) and axis != in_axis:
        from ...ops.registry import get_op
        inputs = _nd.invoke(get_op("swapaxes"), [inputs],
                            {"dim1": axis, "dim2": in_axis})
    return inputs, axis, batch_size


def _split_axis(x, num, axis):
    from ...ops.registry import get_op
    from ...ndarray.ndarray import invoke
    outs = []
    for i in range(num):
        outs.append(invoke(get_op("slice_axis"), [x],
                           {"axis": axis, "begin": i, "end": i + 1}))
    return outs


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, list):
        data = _split_axis(data, length, time_axis)
        data = [d.reshape([s for i, s in enumerate(d.shape) if i != time_axis])
                for d in data]
    outputs = []
    for i, x in enumerate(data):
        mask = (valid_length > i).reshape((-1,) + (1,) * (x.ndim - 1))
        outputs.append(F.broadcast_mul(x, mask.astype(x.dtype)))
    if merge:
        outputs = [o.expand_dims(time_axis) for o in outputs]
        outputs = _nd.ndarray.concatenate(outputs, axis=time_axis)
    return outputs


class RecurrentCell(Block):
    """Abstract recurrent cell (ref: rnn_cell.py class RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset before re-unroll (ref: rnn_cell.py reset)."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children:
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    @property
    def _curr_prefix(self):
        return "%st%d_" % (self.prefix, self._counter)

    def begin_state(self, batch_size=0, func=_nd.zeros, **kwargs):
        """Initial states (ref: rnn_cell.py begin_state)."""
        assert not self._modified, \
            "After applying modifier cells (e.g. ZoneoutCell) the base " \
            "cell cannot be called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(**info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over `length` steps (ref: rnn_cell.py unroll)."""
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs, batch_size)

        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = []
            for i in range(len(all_states[0])):
                pieces = [ele[i].expand_dims(0) for ele in all_states]
                stacked = _nd.ndarray.concatenate(pieces, axis=0)
                idx = (valid_length - 1).astype("int32")
                gathered = F.take(stacked, idx, axis=0)
                # take diag over batch: state at its own valid step
                import jax.numpy as jnp
                from ...ndarray import NDArray
                v = gathered._read()
                bi = jnp.arange(v.shape[1])
                states.append(NDArray(v[bi, bi], ctx=gathered.context))
            outputs = _mask_sequence_variable_length(F, outputs, length,
                                                    valid_length, axis, True)
            merge_outputs = True

        if merge_outputs:
            outputs = [o.expand_dims(axis) for o in outputs]
            outputs = _nd.ndarray.concatenate(outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """ref: rnn_cell.py class HybridRecurrentCell."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell: act(W x + R h + b) (ref: rnn_cell.py class RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                        init=initializer.create(i2h_bias_initializer)
                                        if isinstance(i2h_bias_initializer, str)
                                        else i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                        init=initializer.create(h2h_bias_initializer)
                                        if isinstance(h2h_bias_initializer, str)
                                        else h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _pre_infer(self, x, *states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM (ref: rnn_cell.py class LSTMCell; gates [i, f, c, o])."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(4 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(4 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                        init=initializer.create(i2h_bias_initializer)
                                        if isinstance(i2h_bias_initializer, str)
                                        else i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                        init=initializer.create(h2h_bias_initializer)
                                        if isinstance(h2h_bias_initializer, str)
                                        else h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _pre_infer(self, x, *states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4)
        in_gate = F.Activation(slices[0], act_type="sigmoid")
        forget_gate = F.Activation(slices[1], act_type="sigmoid")
        in_transform = F.Activation(slices[2], act_type="tanh")
        out_gate = F.Activation(slices[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU (ref: rnn_cell.py class GRUCell; gates [r, z, n])."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get("i2h_weight",
                                          shape=(3 * hidden_size, input_size),
                                          init=i2h_weight_initializer,
                                          allow_deferred_init=True)
        self.h2h_weight = self.params.get("h2h_weight",
                                          shape=(3 * hidden_size, hidden_size),
                                          init=h2h_weight_initializer,
                                          allow_deferred_init=True)
        self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                        init=initializer.create(i2h_bias_initializer)
                                        if isinstance(i2h_bias_initializer, str)
                                        else i2h_bias_initializer,
                                        allow_deferred_init=True)
        self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                        init=initializer.create(h2h_bias_initializer)
                                        if isinstance(h2h_bias_initializer, str)
                                        else h2h_bias_initializer,
                                        allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _pre_infer(self, x, *states):
        if self.i2h_weight.shape[1] == 0:
            self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_slices = F.SliceChannel(i2h, num_outputs=3)
        h2h_slices = F.SliceChannel(h2h, num_outputs=3)
        i2h_r, i2h_z, i2h_n = i2h_slices[0], i2h_slices[1], i2h_slices[2]
        h2h_r, h2h_z, h2h_n = h2h_slices[0], h2h_slices[1], h2h_slices[2]
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset_gate * h2h_n, act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (ref: rnn_cell.py class SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __getitem__(self, i):
        return self._children[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Dropout on cell outputs (ref: rnn_cell.py class DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float)), "rate must be a number"
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (ref: rnn_cell.py ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified twice" \
            % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=_nd.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (ref: rnn_cell.py class ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            ones = like * 0 + 1
            return F.Dropout(ones, p=p)

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = next_output * 0
        output = (F.where(mask(p_outputs, next_output), next_output, prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Output = cell(x) + x (ref: rnn_cell.py class ResidualCell)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        from ...ndarray import NDArray
        if isinstance(outputs, NDArray):
            inputs, _, _ = _format_sequence(length, inputs, layout, True)
            outputs = outputs + inputs
        else:
            inputs, _, _ = _format_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Two cells over both directions (ref: rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell)
        self.register_child(r_cell)
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError("Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children, batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout, False)
        if valid_length is None:
            reversed_inputs = list(reversed(inputs))
        else:
            # per-sample reverse so padding stays at the tail (ref:
            # rnn_cell.py:933 uses SequenceReverse with sequence_length)
            merged = _nd.concatenate([i.expand_dims(0) for i in inputs], axis=0)
            rev = F.SequenceReverse(merged, valid_length,
                                    use_sequence_length=True)
            reversed_inputs = [rev[i] for i in range(length)]
        begin_state = _get_begin_state(self, F, begin_state, inputs, batch_size)

        states = begin_state
        l_cell, r_cell = self._children
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info(batch_size))],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info(batch_size)):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        if valid_length is None:
            reversed_r_outputs = list(reversed(r_outputs))
        else:
            if isinstance(r_outputs, list):
                r_outputs = _nd.concatenate(
                    [o.expand_dims(0) for o in r_outputs], axis=0)
            elif axis != 0:
                # sub-unroll merged on time axis; bring time to axis 0
                r_outputs = F.swapaxes(r_outputs, dim1=0, dim2=axis)
            rev = F.SequenceReverse(r_outputs, valid_length,
                                    use_sequence_length=True)
            reversed_r_outputs = [rev[i] for i in range(length)]
            if not isinstance(l_outputs, list):
                if axis != 0:
                    l_outputs = F.swapaxes(l_outputs, dim1=0, dim2=axis)
                l_outputs = [l_outputs[i] for i in range(length)]
        outputs = [_nd.concatenate([l_o, r_o], axis=1)
                   for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if merge_outputs or valid_length is not None:
            outputs = [o.expand_dims(axis) for o in outputs]
            outputs = _nd.concatenate(outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
