"""Gluon Block / HybridBlock: define-by-run layers with jit hybridization.

TPU-native rebirth of python/mxnet/gluon/block.py:

* ``Block`` (block.py:123) — imperative container with auto-registered
  children and Parameters, prefix scoping via ``_BlockScope``.
* ``HybridBlock`` (block.py:376) — on ``hybridize()``, the forward is traced
  ONCE per input signature into a **CachedOp = jax.jit of the functionalized
  forward** (block.py:436-439 traces to a symbolic CachedOp; here XLA is the
  graph executor, so tracing and compiling are the same step).  The
  functionalization:
    - parameters enter as pytree leaves (so donation/sharding apply),
    - the framework PRNG is threaded in as an explicit key,
    - in-place parameter writes during the trace (BatchNorm moving stats)
      are detected via the NDArray version counter and returned as extra
      outputs, then written back eagerly — MXNet's mutable aux-state
      semantics preserved over functional XLA.
* Under autograd recording, one tape node is recorded for the whole
  CachedOp with its jax.vjp — mirroring ``_CachedOp``'s fused backward
  (src/imperative/cached_op.cc:434).
"""
from __future__ import annotations

import copy
import threading

import numpy as np
import jax

from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from .. import ndarray as _nd
from ..ops.registry import Operator
from .. import autograd
from .. import random_state
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "functionalize"]


class _BlockScope(object):
    """Name/prefix scope for Blocks (ref: block.py class _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix+params pair for the new Block."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..name import NameManager
                prefix = NameManager.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        from ..name import Prefix
        self._name_scope = Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


def _flatten(args, inout_str):
    """Flatten nested list/tuple of NDArrays (ref: block.py _flatten)."""
    if isinstance(args, NDArray):
        return [args], int(0)
    if args is None:
        return [None], int(-1)
    assert isinstance(args, (list, tuple)), \
        "HybridBlock %s must be (nested) list of NDArray, but got %s of type %s" \
        % (inout_str, str(args), str(type(args)))
    flat = []
    fmts = []
    for i in args:
        arg, fmt = _flatten(i, inout_str)
        flat.extend(arg)
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    """Inverse of _flatten (ref: block.py _regroup)."""
    if isinstance(fmt, int):
        if fmt == -1:
            return None, args[1:]
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block(object):
    """Base class for all neural network layers (ref: block.py:123)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope = _BlockScope(self)
        self._children = []
        self._reg_params = {}

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(str(block), 2))
            for key, block in self.__dict__.items()
            if isinstance(block, Block))
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Auto-register children and params (ref: block.py __setattr__)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and not isinstance(value, type(existing)):
                raise TypeError("Changing attribute type for {name} from {type1} to {type2}"
                                "is not allowed.".format(name=name,
                                                         type1=type(existing),
                                                         type2=type(value)))
            if isinstance(existing, Block):
                for i, c in enumerate(self._children):
                    if c is existing:
                        self._children[i] = value
            elif isinstance(value, Block):
                self.register_child(value)
        elif isinstance(value, Block):
            self.register_child(value)
        if isinstance(value, Parameter):
            assert name not in self._reg_params or self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed. " \
                "If you want to share parameters between blocks, please set " \
                "'params' at Block construction instead." % name
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """ref: block.py name_scope."""
        return self._scope

    @property
    def params(self):
        """ParameterDict of this Block only (not children)."""
        return self._params

    def collect_params(self, select=None):
        """Recursively collect Parameters (ref: block.py collect_params,
        with the 1.3+ `select` regex for forward-compat)."""
        import re
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children:
            ret.update(cld.collect_params(select=select))
        return ret

    def save_params(self, filename):
        """ref: block.py:295 save_params."""
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        """ref: block.py:303 load_params."""
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                   self.prefix)

    def register_child(self, block):
        """ref: block.py register_child."""
        self._children.append(block)

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        """ref: block.py initialize."""
        from .. import initializer
        if init is None:
            init = initializer.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """Recursively activate hybridization on HybridBlock children."""
        for cld in self._children:
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        """ref: block.py cast."""
        for child in self._children:
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    first = lines.pop(0)
    lines = [(num_spaces * " ") + line for line in lines]
    return "\n".join([first] + lines)


class _TraceParam(object):
    """Shadow for a Parameter during CachedOp tracing: .data() returns the
    tracer-backed NDArray; writes land on the shadow and are harvested."""

    __slots__ = ("param", "shadow")

    def __init__(self, param, shadow):
        self.param = param
        self.shadow = shadow


class CachedOp(object):
    """jit-compiled trace of a HybridBlock forward.

    The TPU-native _CachedOp (ref: src/imperative/cached_op.cc): cache key is
    (input shapes/dtypes, train flag) — the reference's static-shape
    specialization (cached_op.cc:179 GetForwardGraph keyed on shapes) becomes
    XLA's compile cache. Bucketed shapes therefore each compile once and hit
    thereafter, which is how BucketingModule-style workloads stay fast.
    """

    def __init__(self, block):
        self.block = block
        self._cache = {}
        # the param set only changes on structural mutation, which calls
        # _clear_cached_op (→ a fresh CachedOp); cache the walk here
        self._params = block._active_params
        self._param_names = sorted(self._params.keys())
        # forward-use order of the params, recorded by first-touch hooks
        # on the first trace (graftstep pull priority; empty until then)
        self.touch_order = []

    def _make_fn(self, param_names, n_inputs, in_fmt, train):
        block = self.block

        def fn(param_vals, input_vals, rng):
            shadows = {name: NDArray(param_vals[name]) for name in param_names}
            if not self.touch_order:
                _install_first_touch(shadows, self.touch_order)
            nd_in = [None if v is None else NDArray(v) for v in input_vals]
            args, _ = _regroup(nd_in, in_fmt)
            if not isinstance(args, list):
                args = [args]
            with random_state.use_key(rng):
                with autograd._scope(recording=False, training=train):
                    with block._trace_params(shadows):
                        out = block.hybrid_forward_dispatch(*args)
            flat_out, out_fmt = _flatten(out, "output")
            out_vals = tuple(o._read() for o in flat_out)
            # harvest in-place writes to parameters (aux states): shadow
            # version counter moved ⇒ the trace mutated it
            aux_updates = {name: sh._read() for name, sh in shadows.items()
                           if sh._version > 0}
            # graftlint: disable=GL304 -- trace-time output-fmt memo, written once per trace
            self._last_out_fmt = out_fmt
            return out_vals, aux_updates

        return fn

    def __call__(self, *args):
        block = self.block
        flat_args, in_fmt = _flatten(args, "input")
        params = self._params
        param_names = self._param_names
        param_vals = {}
        for name in param_names:
            p = params[name]
            if p._data is None:
                if not p._deferred_init or p.shape is None or \
                        0 in p.shape or np.prod(p.shape) <= 0:
                    # unresolved deferred shape (or not initialized): p.data()
                    # raises the right error; forward() catches Deferred and
                    # runs the eager shape-inference pass first
                    p.data()
                p._finish_deferred_init()
            param_vals[name] = p.data()._read()
        input_vals = [None if a is None else a._read() for a in flat_args]
        train = autograd.is_training()
        recording = autograd.is_recording()

        key = (tuple(None if v is None else (v.shape, str(v.dtype))
                     for v in input_vals),
               tuple((param_vals[n].shape, str(param_vals[n].dtype))
                     for n in param_names),
               _fmt_key(in_fmt), train)
        entry = self._cache.get(key)
        if entry is None:
            raw = self._make_fn(param_names, len(input_vals), in_fmt, train)

            def vjp_apply(pv, iv, rng_, cts):
                # forward rematerializes inside the compiled backward — the
                # whole fwd+bwd is one XLA program, no Python re-trace per
                # step (rng_ is the same key, so dropout masks match)
                _, vjp_fn = jax.vjp(lambda p, i: raw(p, i, rng_)[0], pv, iv)
                return vjp_fn(cts)

            entry = {"raw": raw, "jit": jax.jit(raw), "vjp": jax.jit(vjp_apply)}
            self._cache[key] = entry

        rng = random_state.next_key()
        out_vals, aux_updates = entry["jit"](param_vals, input_vals, rng)
        if "out_fmt" not in entry:
            # fn ran (traced) at least once for this entry, setting the fmt
            entry["out_fmt"] = self._last_out_fmt

        ctx = flat_args[0]._ctx if flat_args else current_context()
        out_arrays = [NDArray(v, ctx=ctx) for v in out_vals]

        # write back mutated aux states (moving mean/var)
        for name, val in aux_updates.items():
            params[name].data()._write(val)

        if recording:
            real_idx = [i for i, a in enumerate(flat_args) if a is not None]
            tape_inputs = [params[n].data() for n in param_names] + \
                [flat_args[i] for i in real_idx]

            def tape_vjp(ct):
                cts = ct if isinstance(ct, tuple) else (ct,)
                pv_g, iv_g = entry["vjp"](param_vals, input_vals, rng, cts)
                return tuple(pv_g[n] for n in param_names) + \
                    tuple(iv_g[i] for i in real_idx)

            raw = entry["raw"]
            n_par = len(param_names)

            def tape_fn(*vals):
                # replayable pure function of the tape inputs — lets
                # autograd's create_graph build grad-of-grad through the
                # whole compiled block (same rng → same dropout masks)
                pv = dict(zip(param_names, vals[:n_par]))
                iv = list(input_vals)
                for j, idx in enumerate(real_idx):
                    iv[idx] = vals[n_par + j]
                outs, _aux = raw(pv, iv, rng)
                return outs[0] if len(outs) == 1 else tuple(outs)

            op = Operator("_CachedOp", lambda *a: a,
                          num_inputs=len(tape_inputs),
                          num_outputs=len(out_arrays))
            autograd._record(op, tape_inputs, out_arrays, tape_vjp,
                             fn=tape_fn)

        out, _ = _regroup(out_arrays, entry["out_fmt"])
        return out


def _fmt_key(fmt):
    if isinstance(fmt, list):
        return tuple(_fmt_key(f) for f in fmt)
    return fmt


def _install_first_touch(shadows, order):
    """Arm one-shot first-touch hooks on a trace's shadow parameters:
    the first ``_read`` of each shadow appends its param name to
    ``order`` — the forward-USE order of the block's weights, recorded
    during the trace itself at zero steady-state cost (hooks clear
    themselves on first fire, the PullScheduler convention).  graftstep
    feeds the recorded order into ``Trainer.note_first_touch_order``:
    the duplex pull side then issues weight pulls in the order the next
    forward will consume them, and ``GRAFT_BUCKET_ORDER=touch`` packs
    buckets by it."""
    for name, sh in shadows.items():
        def hook(arr, _name=name):
            arr._touch_hook = None
            order.append(_name)
        sh._touch_hook = hook


class HybridBlock(Block):
    """Block that can be traced+compiled (ref: block.py:376 HybridBlock).

    Subclasses implement ``hybrid_forward(F, x, *, weight=..., ...)``; F is
    the ndarray module eagerly and (conceptually) the symbol module under
    tracing — with XLA, both paths run the same jax ops, so F is always the
    ndarray module and tracing happens at the jax level.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._shadow_tls = threading.local()

    # trace shadows are installed for the DURATION OF A JIT TRACE
    # (_trace_params) — and traces run on whatever thread triggered the
    # compile (the serving batcher's dispatcher, a CachedOp first call).
    # They must be THREAD-LOCAL: a plain attribute would leak another
    # thread's in-flight tracers into a concurrent eager forward on this
    # same block (UnexpectedTracerError at best, silently tracing the
    # eager caller's math at worst).
    @property
    def _trace_shadows(self):
        return getattr(self._shadow_tls, "shadows", None)

    @_trace_shadows.setter
    def _trace_shadows(self, value):
        self._shadow_tls.shadows = value

    @property
    def _active_params(self):
        """name → Parameter used by this block subtree's forward."""
        out = {}
        for name, p in self.collect_params().items():
            out[name] = p
        return out

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, (HybridBlock, Parameter)):
            # a new child OR a new Parameter invalidates the traced graph —
            # the CachedOp snapshots the param set at construction
            self._clear_cached_op()

    def _clear_cached_op(self):
        self._cached_op = None

    def register_child(self, block):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, "
                "but %s has type %s. If you are using Sequential, "
                "please try HybridSequential instead." % (
                    str(block), str(type(block))))
        super().register_child(block)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        """ref: block.py hybridize — subsequent calls compile & cache."""
        self._active = active
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Deferred-shape resolution by a dry trace (ref: block.py infer_shape)."""
        self._deferred_infer_shape(*args)

    def _deferred_infer_shape(self, *args):
        """Run shape inference via jax.eval_shape over the eager forward to
        fill deferred parameter shapes (ref: block.py _deferred_infer_shape
        which re-infers through the symbolic graph)."""
        try:
            self.forward_eager_infer(*args)
        except DeferredInitializationError:
            raise
        except Exception as e:
            raise ValueError("Deferred initialization failed because shape "
                             "cannot be inferred: %s" % e)

    def forward_eager_infer(self, *args):
        # default: child blocks implement shape hints via their own
        # hybrid_forward's deferred logic (each layer fills in its params)
        pass

    # dispatch helper used by both eager and traced paths
    def hybrid_forward_dispatch(self, *args):
        params = {}
        shadows = self._trace_shadows
        deferred = [p for p in self._reg_params.values()
                    if p._data is None and p._deferred_init]
        if deferred and (shadows is None or
                         any(p.name not in shadows for p in deferred)):
            # layer-local shape inference from the live input (the reference
            # resolves deferred shapes via symbolic infer_shape,
            # block.py _deferred_infer_shape; here each layer fills its own)
            self._pre_infer(*args)
            for p in deferred:
                p._finish_deferred_init()
        for name, p in self._reg_params.items():
            if shadows is not None and p.name in shadows:
                params[name] = shadows[p.name]
            else:
                params[name] = p.data()
        from .. import ndarray as F
        return self.hybrid_forward(F, *args, **params)

    def _pre_infer(self, *args):
        """Fill deferred parameter shapes from the first input. Layers with
        in_units/in_channels==0 override this."""
        return

    from contextlib import contextmanager

    @contextmanager
    def _trace_params(self, shadows):
        """Install shadow tracer NDArrays for all params in the subtree."""
        stack = [self]
        blocks = []
        while stack:
            b = stack.pop()
            blocks.append(b)
            stack.extend(b._children)
        prev = [getattr(b, "_trace_shadows", None) for b in blocks]
        for b in blocks:
            b._trace_shadows = shadows
        try:
            yield
        finally:
            for b, p in zip(blocks, prev):
                b._trace_shadows = p

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, x, *args):
        """Defines the forward computation (ref: block.py:561 forward)."""
        if self._trace_shadows is not None:
            # inside an enclosing CachedOp trace: inline into the parent's
            # single jit (the reference inlines subgraphs too, cached_op.cc:69)
            return self.hybrid_forward_dispatch(x, *args)
        if self._active:
            if self._cached_op is None:
                self._cached_op = CachedOp(self)
            try:
                return self._cached_op(x, *args)
            except DeferredInitializationError:
                self._run_deferred_init(x, *args)
                return self._cached_op(x, *args)
        try:
            return self.hybrid_forward_dispatch(x, *args)
        except DeferredInitializationError:
            self._run_deferred_init(x, *args)
            return self.hybrid_forward_dispatch(x, *args)

    def _run_deferred_init(self, *args):
        """First-call shape resolution: one eager pass lets every layer in
        the subtree fill its own deferred parameter shapes."""
        with autograd.pause():
            self.hybrid_forward_dispatch(*args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Override to define the computation (ref: block.py hybrid_forward)."""
        raise NotImplementedError

    def serving_fn(self, *example_args, train=False):
        """graftserve forward entry point: ``(fn, param_vals)`` where
        ``fn(param_vals, *input_vals)`` is the pure jittable inference
        forward (the same functionalized trace ``CachedOp`` compiles)
        and ``param_vals`` the name→raw-array weight snapshot the
        serving :class:`~incubator_mxnet_tpu.serving.ModelRegistry`
        treats as the residency unit.  One ``jax.jit`` of ``fn`` serves
        every (shape-bucket) batch as ONE device call — XLA's compile
        cache keys on the padded batch signature."""
        return functionalize(self, *example_args, train=train)


class SymbolBlock(HybridBlock):
    """Build a HybridBlock from a Symbol (ref: block.py:599 SymbolBlock).

    Constructed lazily: the symbol executor lives in the symbol module
    (phase 5); SymbolBlock wraps its traced callable.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from ..symbol import Symbol
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if not isinstance(outputs, Symbol):
            raise TypeError("outputs must be a Symbol")
        syms = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._in_names = [s.name for s in syms]
        self._sym = outputs
        # register all non-input arguments as parameters
        arg_names = [n for n in outputs.list_arguments() if n not in self._in_names]
        aux_names = list(outputs.list_auxiliary_states())
        for n in arg_names:
            self.params.get(n.removeprefix(self.params.prefix) if n.startswith(self.params.prefix) else n,
                            allow_deferred_init=True, grad_req="write")
        for n in aux_names:
            self.params.get(n.removeprefix(self.params.prefix) if n.startswith(self.params.prefix) else n,
                            allow_deferred_init=True, grad_req="null")

    def forward(self, *args):
        in_map = dict(zip(self._in_names, args))
        param_map = {}
        for name, p in self.params.items():
            short = name[len(self.params.prefix):] if name.startswith(self.params.prefix) else name
            param_map[short] = p.data()
        merged = dict(param_map)
        merged.update(in_map)
        return self._sym.eval_dict(merged)

    def hybrid_forward(self, F, *args, **kwargs):  # pragma: no cover
        raise NotImplementedError


def functionalize(block, *example_args, train=False):
    """Extract the pure jittable forward of a HybridBlock.

    Returns ``(fn, param_vals)`` where ``fn(param_vals, *input_vals)``
    maps raw jax arrays to raw jax array outputs (a single array, or a
    tuple when the block returns several).  This is the same
    functionalized trace ``CachedOp`` compiles per signature — exposed
    so callers can compose the forward into LARGER XLA programs
    (``lax.scan`` chains for steady-state serving benchmarks, custom
    pjit shardings, export pipelines) instead of paying one dispatch per
    call.  ref: src/imperative/cached_op.cc — the reference's _CachedOp
    handle plays this role for its graph executor.

    ``example_args`` resolve deferred shapes with one eager pass;
    ``train`` picks the training/inference trace (BatchNorm stats etc.).
    Aux-state writes inside the trace (moving averages) are DISCARDED —
    use the block's normal call path for stateful training.

    RNG ops (dropout etc.) draw from the ``rng`` keyword — a jax PRNG
    key that is part of the traced signature, exactly as in CachedOp's
    compiled trace.  It defaults to a FIXED key: stochastic blocks must
    pass a fresh ``rng=`` per call or every call reuses the same masks.
    """
    import jax as _jax
    from ..ndarray import NDArray
    from .. import autograd

    block(*[NDArray(a) if not isinstance(a, NDArray) else a
            for a in example_args])        # resolve deferred init
    params = block.collect_params()
    param_vals = {name: p.data()._read() for name, p in params.items()}

    def fn(param_vals, *input_vals, rng=None):
        if rng is None:
            rng = _jax.random.PRNGKey(0)
        shadows = {name: NDArray(v) for name, v in param_vals.items()}
        nd_in = [NDArray(v) for v in input_vals]
        with random_state.use_key(rng):
            with autograd._scope(recording=False, training=train):
                with block._trace_params(shadows):
                    out = block.hybrid_forward_dispatch(*nd_in)
        flat, _fmt = _flatten(out, "output")
        vals = tuple(o._read() for o in flat)
        return vals[0] if len(vals) == 1 else vals

    return fn, param_vals
