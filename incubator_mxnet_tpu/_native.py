"""Loader for the native C++ components (src/ → build/*.so).

The data plane (RecordIO parsing, threaded prefetch) and the C predict
ABI are native code like the reference's (SURVEY §1 layers 7/8); Python
binds them through ctypes.  Everything degrades gracefully: when the
libraries are absent and the toolchain can't build them, the pure-Python
paths serve instead.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

_SRC_DIR = os.path.join(os.path.dirname(__file__), "..", "src")
_BUILD_DIR = os.path.join(_SRC_DIR, "build")

_io_lib = None
_io_tried = False


def _try_build():
    try:
        subprocess.run(["make", "-C", _SRC_DIR],
                       capture_output=True, timeout=120, check=True)
        return True
    except Exception:
        return False


def _load(name):
    path = os.path.join(_BUILD_DIR, name)
    if not os.path.exists(path):
        if not _try_build():
            return None
    if not os.path.exists(path):
        return None
    try:
        return ctypes.CDLL(path)
    except OSError:
        return None


def io_lib():
    """The RecordIO native library, or None (pure-Python fallback)."""
    global _io_lib, _io_tried
    if _io_tried:
        return _io_lib
    _io_tried = True
    lib = _load("libmxtpu_io.so")
    if lib is not None:
        lib.MXTPURecordIOReaderCreate.restype = ctypes.c_void_p
        lib.MXTPURecordIOReaderCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPURecordIOReaderNext.restype = ctypes.c_int
        lib.MXTPURecordIOReaderNext.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.MXTPURecordIOReaderSeek.argtypes = [ctypes.c_void_p,
                                                ctypes.c_uint64]
        lib.MXTPURecordIOReaderTell.restype = ctypes.c_uint64
        lib.MXTPURecordIOReaderTell.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOReaderFree.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOWriterCreate.restype = ctypes.c_void_p
        lib.MXTPURecordIOWriterCreate.argtypes = [ctypes.c_char_p]
        lib.MXTPURecordIOWriterWrite.restype = ctypes.c_int
        lib.MXTPURecordIOWriterWrite.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.MXTPURecordIOWriterTell.restype = ctypes.c_uint64
        lib.MXTPURecordIOWriterTell.argtypes = [ctypes.c_void_p]
        lib.MXTPURecordIOWriterFree.argtypes = [ctypes.c_void_p]
        lib.MXTPUPrefetchReaderCreate.restype = ctypes.c_void_p
        lib.MXTPUPrefetchReaderCreate.argtypes = [ctypes.c_char_p,
                                                  ctypes.c_uint64]
        lib.MXTPUPrefetchReaderNext.restype = ctypes.c_int
        lib.MXTPUPrefetchReaderNext.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.MXTPUPrefetchReaderFree.argtypes = [ctypes.c_void_p]
    _io_lib = lib
    return lib


class NativeRecordReader(object):
    """Sequential reader over libmxtpu_io (dmlc wire format)."""

    def __init__(self, path):
        lib = io_lib()
        if lib is None:
            raise OSError("native IO library unavailable")
        self._lib = lib
        self._h = lib.MXTPURecordIOReaderCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        out = ctypes.c_char_p()
        size = ctypes.c_uint64()
        ok = self._lib.MXTPURecordIOReaderNext(self._h, ctypes.byref(out),
                                               ctypes.byref(size))
        if not ok:
            return None
        return ctypes.string_at(out, size.value)

    def seek(self, pos):
        self._lib.MXTPURecordIOReaderSeek(self._h, pos)

    def tell(self):
        return self._lib.MXTPURecordIOReaderTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOReaderFree(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeRecordWriter(object):
    """Sequential writer over libmxtpu_io."""

    def __init__(self, path):
        lib = io_lib()
        if lib is None:
            raise OSError("native IO library unavailable")
        self._lib = lib
        self._h = lib.MXTPURecordIOWriterCreate(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def write(self, data):
        data = bytes(data)
        if self._lib.MXTPURecordIOWriterWrite(self._h, data, len(data)) != 0:
            raise IOError("native RecordIO write failed")

    def tell(self):
        return self._lib.MXTPURecordIOWriterTell(self._h)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOWriterFree(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativePrefetchReader(object):
    """Background-thread record reader (ThreadedIter's role): file IO and
    record framing proceed while Python decodes the previous batch."""

    def __init__(self, path, capacity=16):
        lib = io_lib()
        if lib is None:
            raise OSError("native IO library unavailable")
        self._lib = lib
        self._h = lib.MXTPUPrefetchReaderCreate(path.encode(), capacity)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        out = ctypes.c_char_p()
        size = ctypes.c_uint64()
        ok = self._lib.MXTPUPrefetchReaderNext(self._h, ctypes.byref(out),
                                               ctypes.byref(size))
        if not ok:
            return None
        return ctypes.string_at(out, size.value)

    def close(self):
        if self._h:
            self._lib.MXTPUPrefetchReaderFree(self._h)
            self._h = None

    def __del__(self):
        self.close()


def available():
    return io_lib() is not None
