"""Shared base utilities for the TPU-native framework.

Plays the role of the reference's ``python/mxnet/base.py`` (ctypes bridge,
error type, name manager) — but there is no C library to load: the compute
substrate is JAX/XLA, so "the library" is the in-process op registry
(see ``ops/registry.py``). Reference: python/mxnet/base.py:1-120.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ["MXNetError", "NameManager", "string_types", "numeric_types"]

string_types = (str,)
numeric_types = (float, int, np.generic)


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


class NameManager:
    """Automatic unique-name assignment for symbols/blocks.

    Mirrors python/mxnet/name.py: a thread-local stack of managers;
    ``get(None, hint)`` manufactures ``hint0, hint1, ...``.
    """

    _local = threading.local()

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name is not None:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(NameManager._local, "stack"):
            NameManager._local.stack = [NameManager()]
        NameManager._local.stack.append(self)
        return self

    def __exit__(self, *args):
        NameManager._local.stack.pop()

    @staticmethod
    def current():
        if not hasattr(NameManager._local, "stack"):
            NameManager._local.stack = [NameManager()]
        return NameManager._local.stack[-1]


class Prefix(NameManager):
    """Name manager that always attaches a prefix (mxnet.name.Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
