"""Custom operators written in Python, usable from NDArray AND Symbol.

TPU-native rebirth of python/mxnet/operator.py (CustomOp:422,
CustomOpProp:468, register:~600) + src/operator/custom/custom-inl.h:50-134
(the C++ CustomOperator registry with its GIL-safe callback queue).

Design: the reference marshals Python callbacks through the engine's
worker threads; here each registered custom op becomes a real registry
Operator whose fcompute escapes to the host via ``jax.pure_callback`` —
so custom Python ops work in eager mode, inside ``jax.jit``, and inside
compiled Symbol executors alike.  Gradients route through
``jax.custom_vjp`` to the user's ``backward`` (also a host callback).

The (unavoidable) cost is a device→host→device round trip per call, the
same penalty the reference pays for leaving the engine; everything
around the custom node stays fused on the TPU.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import Operator, _REGISTRY, _log_registration

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]

_CUSTOM_PROPS = {}


class CustomOp(object):
    """Base class for the runtime part of a custom operator
    (ref: operator.py CustomOp:422)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs: write into ``out_data`` via :meth:`assign`."""
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients into ``in_grad`` via :meth:`assign`."""
        raise NotImplementedError

    def assign(self, dst, req, src):
        """ref: operator.py CustomOp.assign — honors req null/write/add."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise ValueError("unknown req %r" % req)


class CustomOpProp(object):
    """Static properties of a custom operator (ref: CustomOpProp:468)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: all outputs/aux take the first input's shape."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), \
            [in_shape[0]] * len(self.list_auxiliary_states())

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def _as_ndarrays(np_arrays):
    from .ndarray import NDArray
    return [NDArray(jnp.asarray(a)) for a in np_arrays]


def _make_custom_operator(op_type, prop_cls):
    """Build a registry Operator for one registered CustomOpProp.

    The prop is instantiated lazily with the call-site params (stock
    MXNet's pattern — props commonly have required __init__ args), so
    arity and output count are functions of the params via
    fargnames/fnum_outputs."""

    def make_prop(params):
        kwargs = {k: str(v) for k, v in params.items()
                  if k not in ("op_type", "is_train")}
        return prop_cls(**kwargs)

    def fcompute(*inputs, is_train=False, **params):
        prop = make_prop(params)
        n_out = len(prop.list_outputs())
        in_shapes = [tuple(x.shape) for x in inputs]
        in_dtypes = [x.dtype for x in inputs]
        _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
        _, out_types, _ = prop.infer_type(list(in_dtypes))
        result_spec = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                            for s, t in zip(out_shapes, out_types))

        def host_forward(*np_in):
            op = prop.create_operator(None, in_shapes, in_dtypes)
            in_nd = _as_ndarrays(np_in)
            out_nd = _as_ndarrays([np.zeros(s, t)
                                   for s, t in zip(out_shapes, out_types)])
            # req='write' mirrors the reference's imperative dispatch
            # (graph-planned kAddTo never reaches eager custom calls)
            op.forward(is_train=is_train, req=["write"] * len(out_nd),
                       in_data=in_nd, out_data=out_nd, aux=[])
            return tuple(np.asarray(o.asnumpy(), t)
                         for o, t in zip(out_nd, out_types))

        def host_backward(*np_all):
            grads = np_all[:n_out]
            ins = np_all[n_out:n_out + len(in_shapes)]
            outs = np_all[n_out + len(in_shapes):]
            op = prop.create_operator(None, in_shapes, in_dtypes)
            in_nd = _as_ndarrays(ins)
            out_nd = _as_ndarrays(outs)
            grad_nd = _as_ndarrays(grads)
            igrad_nd = _as_ndarrays([np.zeros(s, d)
                                     for s, d in zip(in_shapes, in_dtypes)])
            op.backward(req=["write"] * len(igrad_nd), out_grad=grad_nd,
                        in_data=in_nd, out_data=out_nd, in_grad=igrad_nd,
                        aux=[])
            return tuple(np.asarray(g.asnumpy(), d)
                         for g, d in zip(igrad_nd, in_dtypes))

        @jax.custom_vjp
        def run(*xs):
            out = jax.pure_callback(host_forward, result_spec, *xs)
            return tuple(out) if n_out > 1 else out[0]

        def run_fwd(*xs):
            out = jax.pure_callback(host_forward, result_spec, *xs)
            res = tuple(out) if n_out > 1 else out[0]
            return res, (xs, tuple(out))

        def run_bwd(saved, cts):
            xs, outs = saved
            cts_t = cts if isinstance(cts, tuple) else (cts,)
            in_spec = tuple(jax.ShapeDtypeStruct(tuple(s), d)
                            for s, d in zip(in_shapes, in_dtypes))
            gin = jax.pure_callback(host_backward, in_spec,
                                    *cts_t, *xs, *outs)
            return tuple(gin)

        run.defvjp(run_fwd, run_bwd)
        return run(*inputs)

    def fargnames(params):
        return list(make_prop(params).list_arguments())

    def fnum_outputs(params):
        return len(make_prop(params).list_outputs())

    return Operator("_custom_" + op_type, fcompute, num_inputs=None,
                    num_outputs=1, takes_is_train=True,
                    fargnames=fargnames, fnum_outputs=fnum_outputs,
                    doc="Custom op %r (prop %s; ref: operator.py register)"
                        % (op_type, prop_cls.__name__))


def register(reg_name):
    """Decorator registering a CustomOpProp under ``op_type=reg_name``
    (ref: operator.py register / MXCustomOpRegister)."""

    def dec(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register must wrap a CustomOpProp subclass")
        _CUSTOM_PROPS[reg_name] = prop_cls
        op = _make_custom_operator(reg_name, prop_cls)
        _log_registration(op.name, op)
        _REGISTRY[op.name] = op
        return prop_cls

    return dec


def get_all_registered_operators():
    return sorted(_CUSTOM_PROPS)


def _dispatch_custom(op_type):
    try:
        return _REGISTRY["_custom_" + op_type]
    except KeyError:
        raise MXNetError("Custom op type %r is not registered "
                         "(have: %s)" % (op_type,
                                         get_all_registered_operators()))


def custom_nd(*args, op_type=None, **kwargs):
    """``nd.Custom(*data, op_type='name', **params)``
    (ref: custom.cc Custom op)."""
    from .ndarray.ndarray import invoke
    if op_type is None:
        raise TypeError("Custom requires op_type=")
    op = _dispatch_custom(op_type)
    out = kwargs.pop("out", None)
    name = kwargs.pop("name", None)
    return invoke(op, list(args), kwargs, out=out)


def custom_sym(*args, op_type=None, name=None, **kwargs):
    """``sym.Custom(*data, op_type='name', **params)``."""
    from .symbol.symbol import _make_node
    if op_type is None:
        raise TypeError("Custom requires op_type=")
    op = _dispatch_custom(op_type)
    return _make_node(op, list(args), kwargs, name=name)
