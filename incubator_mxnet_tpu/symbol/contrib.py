"""``sym.contrib`` namespace — short names over the ``_contrib_*`` ops.

Parity: python/mxnet/symbol/contrib.py.
"""
from __future__ import annotations

from ..ops.registry import _REGISTRY
from .register import make_sym_func

__all__ = []
for _name, _op in list(_REGISTRY.items()):
    if _name.startswith("_contrib_"):
        _short = _name[len("_contrib_"):]
        globals()[_short] = make_sym_func(_short, _op)
        __all__.append(_short)
