"""Symbol: declarative graph construction.

TPU-native rebirth of python/mxnet/symbol/symbol.py (2,848 LoC) + the NNVM
graph (src/nnvm/):

* A Symbol is a node in a static dataflow graph over the SAME operator
  registry the eager NDArray path uses (one registry, two modes — MXNet's
  defining design, SURVEY headline idea #2).
* ``bind``/``simple_bind`` return an Executor whose forward compiles the
  whole graph through jax.jit — the reference's GraphExecutor passes
  (PlanMemory, inplace, op fusion, engine bulking; graph_executor.cc:512)
  are all owned by XLA here.
* ``tojson``/``load`` keep an MXNet-style JSON serialization (nodes with
  op/name/attrs/inputs) so checkpoint workflows survive
  (ref: src/nnvm/legacy_json_util.cc versioned JSON).
"""
from __future__ import annotations

import json

import numpy as np

from ..base import MXNetError
from ..context import current_context
from ..ops.registry import get_op, Operator, _REGISTRY
from ..name import NameManager
from .. import attribute

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json"]


class Symbol(object):
    """A node (or node-output) of the symbolic graph."""

    def __init__(self, op=None, inputs=None, params=None, name=None,
                 num_outputs=1, out_index=None, attrs=None):
        self._op = op                      # Operator or None (variable/group)
        self._inputs = inputs or []        # list[Symbol]
        self._params = params or {}        # static attrs
        self._name = name
        self._num_outputs = num_outputs
        self._out_index = out_index        # not None → single output view
        self._attr = dict(attrs or {})
        self._group = None                 # list[Symbol] if this is a Group
        self._view_of = None               # base node if this is an output view

    # -- identity ----------------------------------------------------------
    @property
    def name(self):
        return self._name

    def attr(self, key):
        """ref: symbol.py attr."""
        return self._attr.get(key)

    def _set_attr(self, **kwargs):
        self._attr.update(kwargs)

    def list_attr(self):
        return dict(self._attr)

    def attr_dict(self):
        """name → attrs for the whole graph (ref: symbol.py attr_dict)."""
        ret = {}
        for node in self._topo():
            if node._attr:
                ret[node._name] = dict(node._attr)
        return ret

    def __repr__(self):
        if self._group is not None:
            return "<Symbol group [%s]>" % ", ".join(s.name or "?" for s in self._group)
        return "<Symbol %s>" % self._name

    # -- graph walking -----------------------------------------------------
    def _roots(self):
        if self._group is not None:
            return list(self._group)
        return [self]

    def _topo(self):
        """Topological order of graph nodes (inputs before consumers)."""
        seen = {}
        order = []

        def visit(node):
            base = node._base()
            if id(base) in seen:
                return
            seen[id(base)] = True
            for i in base._inputs:
                visit(i._base())
            order.append(base)
        for r in self._roots():
            visit(r)
        return order

    def _base(self):
        """Strip output-view indirection."""
        return self._view_of if self._view_of is not None else self

    # -- composition -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace variable inputs (ref: symbol.py __call__)."""
        s = self._deepcopy()
        s._compose(*args, **kwargs)
        return s

    def _deepcopy(self, memo=None):
        if memo is None:
            memo = {}
        if id(self) in memo:
            return memo[id(self)]
        if self._view_of is not None:
            base = self._view_of._deepcopy(memo)
            cp = base[self._out_index]
            memo[id(self)] = cp
            return cp
        cp = Symbol(self._op, [i._deepcopy(memo) for i in self._inputs],
                    dict(self._params), self._name, self._num_outputs,
                    self._out_index, dict(self._attr))
        if self._group is not None:
            cp._group = [g._deepcopy(memo) for g in self._group]
        memo[id(self)] = cp
        return cp

    def _compose(self, *args, **kwargs):
        name = kwargs.pop("name", None)
        if name:
            self._name = name
        if args and kwargs:
            raise TypeError("compose only accept input Symbols "
                            "either as positional or keyword arguments, not both")
        arg_names = [i.name for i in self._free_variables()]
        if args:
            kwargs = dict(zip(arg_names, args))
        for node in self._topo():
            new_inputs = []
            for i in node._inputs:
                if i._base().is_variable() and i._base().name in kwargs:
                    new_inputs.append(kwargs[i._base().name])
                else:
                    new_inputs.append(i)
            node._inputs = new_inputs

    def is_variable(self):
        return self._op is None and self._group is None

    def _free_variables(self):
        return [n for n in self._topo() if n.is_variable()]

    # -- listing -----------------------------------------------------------
    def list_arguments(self):
        """Variable names in topo order (ref: symbol.py list_arguments)."""
        return [n.name for n in self._free_variables()
                if not n._attr.get("__aux__")]

    def list_auxiliary_states(self):
        """ref: symbol.py list_auxiliary_states — aux-flagged variables
        (BatchNorm moving stats)."""
        return [n.name for n in self._free_variables()
                if n._attr.get("__aux__")]

    def list_outputs(self):
        outs = []
        for r in self._roots():
            base_name = r._name or "out"
            if r.is_variable():
                outs.append(base_name)
            elif r._num_outputs == 1 or r._out_index is not None:
                outs.append(base_name + "_output")
            else:
                outs.extend("%s_output%d" % (base_name, i)
                            for i in range(r._num_outputs))
        return outs

    def get_internals(self):
        """All intermediate outputs as a group (ref: symbol.py get_internals)."""
        nodes = [n for n in self._topo()]
        return Group([n if n._num_outputs == 1 else n[0] for n in nodes])

    def __getitem__(self, index):
        if self._group is not None:
            if isinstance(index, str):
                names = self.list_outputs()
                index = names.index(index)
            return self._group[index]
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        if index >= self._num_outputs:
            raise IndexError("Index: %d is greater than the number of outputs: %d."
                             % (index, self._num_outputs))
        if self._num_outputs == 1:
            return self
        view = Symbol(self._op, self._inputs, self._params, self._name,
                      self._num_outputs, out_index=index, attrs=self._attr)
        view._view_of = self
        return view

    @property
    def num_outputs(self):
        if self._group is not None:
            return len(self._group)
        return 1 if self._out_index is not None else self._num_outputs

    # -- arithmetic composition -------------------------------------------
    def _binop(self, other, op_name, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _make_node(get_op(op_name), [a, b], {})
        if isinstance(other, (int, float, bool, np.generic)):
            return _make_node(get_op(scalar_op), [self],
                              {"scalar": float(other)})
        raise TypeError("type %s not supported" % str(type(other)))

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, (int, float)):
            return _make_node(get_op("_rminus_scalar"), [self],
                              {"scalar": float(o)})
        return self._binop(o, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    __div__ = __truediv__

    def __rtruediv__(self, o):
        if isinstance(o, (int, float)):
            return _make_node(get_op("_rdiv_scalar"), [self],
                              {"scalar": float(o)})
        return self._binop(o, "elemwise_div", "_div_scalar", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _make_node(get_op("negative"), [self], {})

    def __copy__(self):
        return self._deepcopy()

    def __deepcopy__(self, memo):
        return self._deepcopy()

    def __eq__(self, o):
        if isinstance(o, (Symbol, int, float)):
            try:
                return self._binop(o, "broadcast_equal", "_equal_scalar")
            except Exception:
                return NotImplemented
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Symbol, int, float)):
            try:
                return self._binop(o, "broadcast_not_equal",
                                   "_not_equal_scalar")
            except Exception:
                return NotImplemented
        return NotImplemented

    def __bool__(self):
        raise NotImplementedError(
            "The truth value of a Symbol is ambiguous (it is a graph node, "
            "not a value); use identity checks (`is`) for membership.")

    __hash__ = object.__hash__

    # -- inference ---------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Infer shapes (ref: symbol.py infer_shape). Returns
        (arg_shapes, out_shapes, aux_shapes)."""
        try:
            res = self._infer_shape_impl(False, *args, **kwargs)
            if res[1] is None:
                arg_shapes, _, _ = self._infer_shape_impl(True, *args, **kwargs)
                arg_names = self.list_arguments()
                unknowns = []
                for name, shape in zip(arg_names, arg_shapes or
                                       [None] * len(arg_names)):
                    if not shape or 0 in shape:
                        unknowns.append("%s: %s" % (name, str(shape)))
                import warnings
                warnings.warn("Cannot decide shape for the following arguments "
                              "(0s in shape means unknown dimensions). "
                              "Consider providing them as input:\n\t" +
                              "\n\t".join(unknowns), stacklevel=2)
            return res
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        """ref: symbol.py infer_shape_partial."""
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        if args and kwargs:
            raise ValueError("Can only specify known argument shapes either by "
                            "positional or kwargs way.")
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = shape
        else:
            known.update({k: v for k, v in kwargs.items() if v is not None})
        shapes, _, ok = self._propagate_shapes(known, partial)
        if not ok and not partial:
            return (None, None, None)
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        out_shapes = []
        for r in self._roots():
            b = r._base()
            if b.is_variable():
                out_shapes.append(shapes.get(b.name))
            else:
                out_shapes.append(shapes.get(_out_key(b, r._out_index or 0)))
        return (arg_shapes, out_shapes, aux_shapes)

    def _propagate_shapes(self, known, partial, known_dtypes=None):
        """Forward shape+dtype propagation via op.infer (jax.eval_shape) —
        FInferShape and FInferType in one pass, so the two can't disagree."""
        shapes = dict(known)
        dtypes = dict(known_dtypes or {})
        ok = True
        topo = self._topo()
        for node in topo:
            if node.is_variable():
                if shapes.get(node.name) is None:
                    declared = node._attr.get("__shape__")
                    if declared and 0 not in declared:
                        shapes[node.name] = tuple(declared)
                if dtypes.get(node.name) is None:
                    declared = node._attr.get("__dtype__")
                    if declared:
                        dtypes[node.name] = np.dtype(declared)
                continue
            in_keys = []
            for i in node._inputs:
                b = i._base()
                if b.is_variable():
                    in_keys.append(b.name)
                else:
                    in_keys.append(_out_key(b, i._out_index or 0))
            if any(k not in shapes for k in in_keys):
                # bidirectional half of FInferShape: fill parameter-variable
                # shapes from the (known) data shape via the op's
                # finfer_params (ref: convolution.cc FInferShape fills
                # weight/bias from dshape)
                filled = False
                if node._op.finfer_params is not None and in_keys and \
                        in_keys[0] in shapes:
                    pshapes = node._op.finfer_params(tuple(shapes[in_keys[0]]),
                                                     node._params)
                    req = node._op.arg_names(node._params) or []
                    for iname, key, inp in zip(req, in_keys, node._inputs):
                        if key not in shapes and inp._base().is_variable() \
                                and iname in pshapes:
                            shapes[key] = tuple(pshapes[iname])
                            filled = True
                if any(k not in shapes for k in in_keys):
                    ok = False
                    continue
            in_shapes = [(tuple(shapes[k]), dtypes.get(k, np.float32))
                         for k in in_keys]
            try:
                outs = node._op.infer(in_shapes, node._params)
            except Exception as e:
                if partial:
                    ok = False
                    continue
                raise MXNetError("Error in operator %s: %s" % (node._name, e))
            for i, (shape, dtype) in enumerate(outs):
                shapes[_out_key(node, i)] = shape
                dtypes[_out_key(node, i)] = dtype
        # complete iff every variable got a shape (consumers may have
        # back-filled them after their visit) and every root resolved
        for node in topo:
            if node.is_variable() and shapes.get(node.name) is None:
                ok = False
        for r in self._roots():
            b = r._base()
            key = b.name if b.is_variable() else _out_key(b, r._out_index or 0)
            if shapes.get(key) is None:
                ok = False
        return shapes, dtypes, ok

    def infer_type(self, *args, **kwargs):
        """ref: symbol.py infer_type.  Real propagation when argument
        shapes are declared (``__shape__`` attrs); otherwise every slot
        takes the seed dtype (the reference's common single-dtype case)."""
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known_dtypes = {}
        if args:
            known_dtypes = {n: np.dtype(a) for n, a in zip(arg_names, args)
                            if a is not None}
        else:
            known_dtypes = {k: np.dtype(v) for k, v in kwargs.items()
                            if v is not None}
        fallback = next(iter(known_dtypes.values()), np.dtype(np.float32))
        # seed every undeclared argument with the fallback so the traced
        # dtypes and the reported arg_types cannot disagree (the reference's
        # uniform-seed FInferType semantics); vars with a __dtype__ attr
        # (e.g. int8 quantized params) keep their declaration
        declared = {n.name for n in self._free_variables()
                    if n._attr.get("__dtype__")}
        for n in arg_names:
            if n not in declared:
                known_dtypes.setdefault(n, fallback)
        shapes, dtypes, ok = self._propagate_shapes({}, True, known_dtypes)
        if not ok:
            # shapes unknown → cannot trace; uniform seed dtype
            return ([fallback] * len(arg_names),
                    [fallback] * len(self._roots()),
                    [fallback] * len(aux_names))
        arg_types = [np.dtype(dtypes.get(n, fallback)) for n in arg_names]
        aux_types = [np.dtype(dtypes.get(n, fallback)) for n in aux_names]
        out_types = []
        for r in self._roots():
            b = r._base()
            key = b.name if b.is_variable() else _out_key(b, r._out_index or 0)
            out_types.append(np.dtype(dtypes.get(key, fallback)))
        return (arg_types, out_types, aux_types)

    # -- serialization -----------------------------------------------------
    def tojson(self):
        """MXNet-style JSON graph (ref: symbol.py tojson / save)."""
        nodes = []
        index = {}
        topo = self._topo()
        for node in topo:
            in_entries = []
            for i in node._inputs:
                in_entries.append([index[id(i._base())], i._out_index or 0, 0])
            entry = {
                "op": "null" if node.is_variable() else node._op.name,
                "name": node._name,
                "inputs": in_entries,
            }
            attrs = dict(node._params)
            if node._attr:
                attrs["__sym_attr__"] = dict(node._attr)
            if attrs:
                entry["attrs"] = {k: json.dumps(v) if not isinstance(v, str)
                                  else v for k, v in attrs.items()}
            index[id(node)] = len(nodes)
            nodes.append(entry)
        heads = [[index[id(r._base())], r._out_index or 0, 0]
                 for r in self._roots()]
        arg_nodes = [index[id(n)] for n in topo if n.is_variable()]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10200]}},
                          indent=2)

    def save(self, fname):
        """ref: symbol.py save."""
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- evaluation --------------------------------------------------------
    def eval_dict(self, value_map):
        """Evaluate with a name→NDArray map; returns output NDArray(s)."""
        from ..ndarray import NDArray
        from ..ndarray.ndarray import invoke
        cache = {}
        for node in self._topo():
            if node.is_variable():
                if node.name not in value_map:
                    raise MXNetError("eval missing input %s" % node.name)
                cache[id(node)] = [value_map[node.name]]
                continue
            ins = []
            for i in node._inputs:
                vals = cache[id(i._base())]
                ins.append(vals[min(i._out_index or 0, len(vals) - 1)])
            out = invoke(node._op, ins, dict(node._params))
            cache[id(node)] = out if isinstance(out, list) else [out]
        results = []
        for r in self._roots():
            vals = cache[id(r._base())]
            results.append(vals[min(r._out_index or 0, len(vals) - 1)])
        return results[0] if len(results) == 1 else results

    def eval(self, ctx=None, **kwargs):
        """ref: symbol.py eval."""
        out = self.eval_dict(kwargs)
        return out if isinstance(out, list) else [out]

    # -- binding -----------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate arrays and bind (ref: symbol.py simple_bind →
        GraphExecutor::Init, graph_executor.cc:512)."""
        from .executor import Executor
        from .. import ndarray as nd
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        # one propagation pass yields both shapes and dtypes (quantized
        # graphs carry int8/int32 slots)
        known_dtypes = {k: np.dtype(v) for k, v in (type_dict or {}).items()}
        shapes, dtypes, ok = self._propagate_shapes(
            {k: tuple(v) for k, v in kwargs.items()}, False, known_dtypes)
        if not ok:
            raise ValueError("cannot infer shapes for all arguments")
        arg_shapes = [shapes[n] for n in arg_names]
        aux_shapes = [shapes[n] for n in aux_names]

        def _reusable(arr, shape, dtype):
            return (tuple(arr.shape) == tuple(shape)
                    and np.dtype(arr.dtype) == np.dtype(dtype))

        shared = shared_buffer if shared_buffer is not None else {}
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            dt = dtypes.get(name, np.float32)
            if shared_exec is not None and name in shared_exec.arg_dict and \
                    _reusable(shared_exec.arg_dict[name], shape, dt):
                args[name] = shared_exec.arg_dict[name]
            elif name in shared and _reusable(shared[name], shape, dt):
                args[name] = shared[name]
            else:
                args[name] = nd.zeros(shape, ctx=ctx, dtype=dt)
                shared[name] = args[name]
        aux = {}
        for name, shape in zip(aux_names, aux_shapes):
            dt = dtypes.get(name, np.float32)
            if shared_exec is not None and name in shared_exec.aux_dict and \
                    _reusable(shared_exec.aux_dict[name], shape, dt):
                aux[name] = shared_exec.aux_dict[name]
            else:
                aux[name] = nd.zeros(shape, ctx=ctx, dtype=dt)
        if isinstance(grad_req, str):
            req_of = {n: grad_req for n in arg_names}
        else:
            req_of = {n: grad_req.get(n, "null") for n in arg_names}
        grad_arrays = {name: nd.zeros(shape, ctx=ctx)
                       for name, shape in zip(arg_names, arg_shapes)
                       if req_of[name] != "null"} or None
        return Executor(self, ctx, args, grad_arrays, grad_req, aux)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """ref: symbol.py bind → Executor."""
        from .executor import Executor
        arg_names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(self.list_auxiliary_states(), aux_states))
        return Executor(self, ctx, args, args_grad, grad_req, aux_states or {})

    # convenience mirrors of the reference's symbol method surface
    def get_name(self):
        return self._name


def _out_key(node, idx=0):
    return "#out#%d#%d" % (id(node), idx)


def _make_node(op, inputs, params, name=None):
    hint = op.name.lower().lstrip("_")
    final_name = NameManager.current().get(name, hint)
    attrs = attribute.current().get(None)
    n_out = (op.fnum_outputs(params) if op.fnum_outputs is not None
             else op.num_outputs)
    return Symbol(op, list(inputs), params, final_name, n_out, attrs=attrs)


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (ref: symbol.py var / Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    attrs = attribute.current().get(attr)
    s = Symbol(None, name=name, attrs=attrs)
    if shape is not None:
        s._attr["__shape__"] = tuple(shape)
    if lr_mult is not None:
        s._attr["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        s._attr["__wd_mult__"] = wd_mult
    if dtype is not None:
        s._attr["__dtype__"] = np.dtype(dtype).name
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        s._attr["__init__"] = init
    for k, v in kwargs.items():
        if k.startswith("__") and k.endswith("__"):
            s._attr[k] = v
    return s


Variable = var


def Group(symbols):
    """Group symbols into one multi-output symbol (ref: symbol.py Group)."""
    if not symbols or any(not isinstance(sym, Symbol) for sym in symbols):
        raise TypeError("Expected a list of symbols as input")
    s = Symbol(name="group")
    s._group = [sym for sym in symbols]
    return s


def _upgrade_legacy_json(graph):
    """Normalize pre-1.0 graph JSON in place (the versioned upgrade pass
    of src/nnvm/legacy_json_util.cc:197): MXNet 0.x wrote op params under
    "param"/"attr" instead of "attrs"."""
    for entry in graph.get("nodes", ()):
        if "attrs" not in entry:
            merged = {}
            merged.update(entry.pop("param", None) or {})
            merged.update(entry.pop("attr", None) or {})
            if merged:
                entry["attrs"] = merged
    return graph


def load_json(json_str):
    """Rebuild a Symbol from JSON (ref: symbol.py load_json +
    legacy_json_util.cc LoadLegacyJSONPass for 0.x files)."""
    graph = _upgrade_legacy_json(json.loads(json_str))
    nodes = []
    for entry in graph["nodes"]:
        op_name = entry["op"]
        attrs = dict(entry.get("attrs", {}))
        sym_attr = attrs.pop("__sym_attr__", None)
        if isinstance(sym_attr, str):
            sym_attr = json.loads(sym_attr)
        parsed = {}
        for k, v in attrs.items():
            if isinstance(v, str):
                try:
                    parsed[k] = json.loads(v)
                except (ValueError, TypeError):
                    parsed[k] = v
            else:
                parsed[k] = v
        if op_name == "null":
            s = Symbol(None, name=entry["name"], attrs=sym_attr)
            if parsed:
                s._attr.update({k: tuple(v) if isinstance(v, list) else v
                                for k, v in parsed.items()})
        else:
            ins = []
            for (nid, out_i, _) in entry["inputs"]:
                src = nodes[nid]
                ins.append(src if out_i == 0 and src.num_outputs == 1
                           else src[out_i])
            op = get_op(op_name)
            n_out = (op.fnum_outputs(parsed) if op.fnum_outputs is not None
                     else op.num_outputs)
            s = Symbol(op, ins, parsed, entry["name"], n_out,
                       attrs=sym_attr)
        nodes.append(s)
    heads = [nodes[nid] if out_i == 0 and nodes[nid].num_outputs == 1
             else nodes[nid][out_i]
             for (nid, out_i, _) in graph["heads"]]
    if len(heads) == 1:
        return heads[0]
    return Group(heads)


def load(fname):
    """ref: symbol.py load."""
    with open(fname) as f:
        return load_json(f.read())
