"""Executor: compiled execution of a bound Symbol graph.

TPU-native rebirth of src/executor/graph_executor.cc + include/mxnet/executor.h:

* ``bind`` → one jitted XLA program per (shapes, is_train) signature; the
  reference's memory planning / inplace / segment-bulking passes
  (graph_executor.cc:903,1341) are XLA's job now.
* ``forward(is_train)`` / ``backward(out_grads)`` keep MXNet's contract:
  outputs appear in ``exec.outputs``, gradients accumulate into the bound
  ``args_grad`` arrays honoring ``grad_req`` write/add/null
  (kWriteTo/kAddTo of the reference).
* The backward pass is the jax.vjp of the same traced function — built once
  and cached, mirroring how GraphExecutor materializes the full fwd+bwd
  graph at bind time (graph_executor.cc:277).
* ``set_monitor_callback`` taps every node output (monitor path,
  graph_executor.cc:121).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from .. import ndarray as nd
from .. import random_state, autograd

__all__ = ["Executor"]


class Executor(object):
    """ref: include/mxnet/executor.h Executor."""

    def __init__(self, symbol, ctx, args, args_grad, grad_req, aux_states):
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else current_context()
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        self.arg_dict = dict(args)
        self.grad_dict = dict(args_grad) if args_grad else {}
        self.aux_dict = dict(aux_states) if aux_states else {}
        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in self._arg_names}
        else:
            self.grad_req = dict(grad_req)
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]
        self.grad_arrays = [self.grad_dict.get(n) for n in self._arg_names]
        self.aux_arrays = [self.aux_dict[n] for n in self._aux_names]
        self.outputs = []
        self._monitor_callback = None
        self._fwd_cache = {}
        self._vjp = None
        self._last_sig = None

    # -- compiled graph function ------------------------------------------
    def _graph_fn(self, is_train):
        """Pure function (arg_vals, aux_vals, rng) -> (outputs, aux_out)."""
        symbol = self._symbol
        monitor = self._monitor_callback

        def fn(arg_vals, aux_vals, rng):
            from ..ndarray.ndarray import invoke
            values = {}
            values.update({n: NDArray(v) for n, v in arg_vals.items()})
            values.update({n: NDArray(v) for n, v in aux_vals.items()})
            cache = {}
            with random_state.use_key(rng):
                with autograd._scope(recording=False, training=is_train):
                    for node_ in symbol._topo():
                        if node_.is_variable():
                            if node_.name not in values:
                                raise MXNetError("executor: input %s not bound"
                                                 % node_.name)
                            cache[id(node_)] = [values[node_.name]]
                            continue
                        ins = []
                        for i in node_._inputs:
                            vals = cache[id(i._base())]
                            ins.append(vals[min(i._out_index or 0,
                                                len(vals) - 1)])
                        out = invoke(node_._op, ins, dict(node_._params))
                        outs = out if isinstance(out, list) else [out]
                        cache[id(node_)] = outs
                        if monitor is not None:
                            for oi, o in enumerate(outs):
                                monitor("%s_output%d" % (node_._name, oi), o)
            results = []
            for r in symbol._roots():
                vals = cache[id(r._base())]
                results.append(vals[min(r._out_index or 0, len(vals) - 1)])
            out_vals = tuple(o._read() for o in results)
            # aux states that were written in place during the trace
            aux_out = {n: values[n]._read() for n in aux_vals
                       if values[n]._version > 0}
            return out_vals, aux_out

        return fn

    def _signature(self, is_train):
        return (tuple((n, tuple(self.arg_dict[n].shape),
                       str(self.arg_dict[n].dtype)) for n in self._arg_names),
                bool(is_train))

    def forward(self, is_train=False, **kwargs):
        """ref: executor.h Forward / graph_executor.cc:81."""
        for name, val in kwargs.items():
            if name not in self.arg_dict:
                raise TypeError("Unknown argument %s" % name)
            self.arg_dict[name]._write(
                val._read().astype(self.arg_dict[name].dtype)
                if isinstance(val, NDArray)
                else jnp.asarray(np.asarray(val),
                                 self.arg_dict[name]._read().dtype))
        sig = self._signature(is_train)
        entry = self._fwd_cache.get(sig)
        if entry is None:
            raw = self._graph_fn(is_train)
            entry = {"raw": raw,
                     "jit": jax.jit(raw) if self._monitor_callback is None
                     else raw}
            self._fwd_cache[sig] = entry
        arg_vals = {n: self.arg_dict[n]._read() for n in self._arg_names}
        aux_vals = {n: self.aux_dict[n]._read() for n in self._aux_names}
        rng = random_state.next_key()
        from .. import profiler as _profiler
        # same treatment as deferred op records: without sync the span is
        # dispatch time of one jitted program, and the event says so
        _span = _profiler.op_span("Executor.forward(%s)"
                                  % (self._symbol.name or "sym"), "symbolic",
                                  args={"device_time": _profiler.want_sync()})
        if _span is not None:
            with _span:
                out_vals, aux_out = entry["jit"](arg_vals, aux_vals, rng)
                if _profiler.want_sync():
                    jax.block_until_ready(out_vals)
        else:
            out_vals, aux_out = entry["jit"](arg_vals, aux_vals, rng)
        for n, v in aux_out.items():
            self.aux_dict[n]._write(v)
        self.outputs = [NDArray(v, ctx=self._ctx) for v in out_vals]
        # stash for backward
        self._last_sig = sig
        self._last_inputs = (arg_vals, aux_vals, rng)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """ref: executor.h Backward — vjp into bound grad arrays with
        grad_req write/add semantics."""
        if self._last_sig is None:
            raise MXNetError("backward called before forward")
        entry = self._fwd_cache[self._last_sig]
        arg_vals, aux_vals, rng = self._last_inputs
        if "vjp" not in entry:
            def vjp_apply(av, xv, rng_, cts):
                _, vjp_fn = jax.vjp(
                    lambda a: entry["raw"](a, xv, rng_)[0], av)
                return vjp_fn(cts)[0]
            entry["vjp"] = jax.jit(vjp_apply)
        if out_grads is None:
            cts = tuple(jnp.ones_like(o._read()) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._read() if isinstance(g, NDArray)
                        else jnp.asarray(g) for g in out_grads)
        grads = entry["vjp"](arg_vals, aux_vals, rng, cts)
        for name in self._arg_names:
            req = self.grad_req.get(name, "null")
            tgt = self.grad_dict.get(name)
            if req == "null" or tgt is None:
                continue
            g = grads[name]
            if req == "add":
                tgt._write(tgt._read() + g.astype(tgt._read().dtype))
            else:
                tgt._write(g.astype(tgt._read().dtype))
                # graftduplex: Module's grad arrays carry the same
                # grad-ready hooks gluon's params do (overlap.
                # BucketScheduler) — each write above is an async XLA
                # rebind, so firing here lets complete buckets put their
                # reduce on the wire while the vjp program is still
                # executing on device.  "add" grads are never final per
                # pass and never fire.  A broken hook must not take the
                # user's backward down (autograd._fire_ready_hook
                # isolates + logs; the scheduler falls back to serial).
                if getattr(tgt, "_grad_ready_hook", None) is not None:
                    autograd._fire_ready_hook(tgt)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (ref: executor.h Reshape). Cheap here:
        a new signature just means a new jit cache entry."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for n, shape in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[n]
            if tuple(cur.shape) == tuple(shape):
                new_args[n] = cur
            else:
                new_args[n] = nd.zeros(shape, ctx=self._ctx)
        new_aux = {}
        for n, shape in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[n]
            new_aux[n] = cur if tuple(cur.shape) == tuple(shape) \
                else nd.zeros(shape, ctx=self._ctx)
        grads = None
        if self.grad_dict:
            grads = {n: nd.zeros(shape, ctx=self._ctx)
                     for n, shape in zip(self._arg_names, arg_shapes)}
        return Executor(self._symbol, self._ctx, new_args, grads,
                        self.grad_req, new_aux)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """ref: executor.py copy_params_from."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                dst = self.arg_dict[name]
                array.copyto(dst)
            elif not allow_extra_params:
                raise ValueError("Find name \"%s\" that is not in the arguments"
                                 % name)
        if aux_params is None:
            return
        for name, array in aux_params.items():
            if name in self.aux_dict:
                array.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise ValueError("Find name %s that is not in the auxiliary "
                                 "states" % name)

    def set_monitor_callback(self, callback):
        """ref: MXExecutorSetMonitorCallback (graph_executor.cc:121).
        Installing a monitor disables jit for this executor so every node
        output can be tapped eagerly (the reference pays a similar sync
        cost when monitoring)."""
        self._monitor_callback = callback
        self._fwd_cache = {}

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def debug_str(self):
        lines = ["Symbol outputs: %s" % ", ".join(self._symbol.list_outputs())]
        for node in self._symbol._topo():
            kind = "var" if node.is_variable() else node._op.name
            lines.append("%s %s <- %s" % (kind, node._name,
                                          [i._base()._name
                                           for i in node._inputs]))
        return "\n".join(lines)
