"""Auto-generation of the ``sym.<op>`` function surface.

Parity with python/mxnet/symbol/register.py — one function per registered
operator, splitting Symbol arguments from static attributes and creating a
graph node. Same registry as the ndarray surface (one registration, both
modes).
"""
from __future__ import annotations

import keyword

from ..ops.registry import _REGISTRY, Operator
from .symbol import Symbol, _make_node


def make_sym_func(op_name: str, op: Operator):
    def generic_op(*args, name=None, attr=None, **kwargs):
        from ..name import NameManager
        from .symbol import var
        inputs = []
        rest = list(args)
        while rest and isinstance(rest[0], Symbol):
            inputs.append(rest.pop(0))
        if rest:
            raise TypeError(
                "%s: positional arguments after Symbols must be keyword "
                "attributes, got %r" % (op_name, rest))
        req = op.arg_names({k: v for k, v in kwargs.items()
                            if not isinstance(v, Symbol)})
        if req is not None:
            # named-input binding + auto-created variables for the missing
            # ones (parity: MXSymbolCreateAtomicSymbol auto-vars named
            # <node>_<input>, e.g. conv0_weight)
            provided = dict(zip(req, inputs))
            for n in req:
                v = kwargs.pop(n, None)
                if isinstance(v, Symbol):
                    provided[n] = v
            final_name = NameManager.current().get(
                name, op.name.lower().lstrip("_"))
            inputs = []
            for n in req:
                if n in provided:
                    inputs.append(provided[n])
                else:
                    v = var("%s_%s" % (final_name, n))
                    if n in op.aux_input_names:
                        v._attr["__aux__"] = True
                    inputs.append(v)
            name = final_name
        else:
            for k in list(kwargs):
                if isinstance(kwargs[k], Symbol):
                    inputs.append(kwargs.pop(k))
        node = _make_node(op, inputs, kwargs, name=name)
        if attr:
            node._attr.update(attr)
        return node

    generic_op.__name__ = op_name
    generic_op.__qualname__ = op_name
    generic_op.__doc__ = (op.doc or "") + "\n\n(auto-generated symbol fn; " \
        "parity: python/mxnet/symbol/register.py codegen)"
    return generic_op


def populate(namespace: dict):
    for name, op in list(_REGISTRY.items()):
        if keyword.iskeyword(name) or not name.replace("_", "a").isidentifier():
            continue
        if name in namespace:
            continue
        namespace[name] = make_sym_func(name, op)
