"""`sym` namespace: Symbol + one generated function per operator.

Parity surface: python/mxnet/symbol/__init__.py.
"""
from __future__ import annotations

import sys

from .symbol import Symbol, var, Variable, Group, load, load_json
from .executor import Executor
from . import register as _register


def zeros(shape, dtype="float32", **kwargs):
    from ..ops.registry import get_op
    from .symbol import _make_node
    return _make_node(get_op("zeros"), [],
                      {"shape": tuple(shape) if not isinstance(shape, int)
                       else (shape,), "dtype": dtype})


def ones(shape, dtype="float32", **kwargs):
    from ..ops.registry import get_op
    from .symbol import _make_node
    return _make_node(get_op("ones"), [],
                      {"shape": tuple(shape) if not isinstance(shape, int)
                       else (shape,), "dtype": dtype})


def trace_to_symbol(x):
    """Build a Symbol from an NDArray's autograd history (used by
    autograd.get_symbol; ref: c_api MXAutogradGetSymbol)."""
    from .. import autograd
    from .symbol import _make_node
    node_of = {}

    def build(arr):
        if id(arr) in node_of:
            return node_of[id(arr)]
        ref = getattr(arr, "_tape_ref", None)
        if ref is None:
            v = var("data%d" % len(node_of))
            node_of[id(arr)] = v
            return v
        tape_node, out_idx = ref
        ins = [build(a) for a in tape_node.inputs]
        node = _make_node(tape_node.op, ins, {})
        out = node if node.num_outputs == 1 else node[out_idx]
        node_of[id(arr)] = out
        return out

    return build(x)


_register.populate(sys.modules[__name__].__dict__)

# sub-namespaces for parity: sym.linalg, sym.contrib
from . import linalg  # noqa: E402,F401
from . import contrib  # noqa: E402,F401


def Custom(*args, **kwargs):
    """Python-defined custom op node (ref: src/operator/custom/custom.cc;
    register via mx.operator.register)."""
    from ..operator import custom_sym
    return custom_sym(*args, **kwargs)
