"""``sym.linalg`` namespace — short names over the ``_linalg_*`` op family.

Parity: python/mxnet/symbol/linalg.py.
"""
from __future__ import annotations

from ..ops.registry import get_op
from .register import make_sym_func

_OPS = ("gemm", "gemm2", "potrf", "potri", "trmm", "trsm", "sumlogdiag",
        "syrk", "gelqf", "syevd")

for _n in _OPS:
    globals()[_n] = make_sym_func(_n, get_op("_linalg_" + _n))

__all__ = list(_OPS)
