"""Global PRNG state for eager random ops.

The reference seeds per-device mshadow/Philox generators via
``mx.random.seed`` (src/resource.cc:160, src/common/random_generator.h).
TPU-natively we keep one root ``jax.random`` key and derive a fresh,
counter-folded subkey per eager random call — deterministic given the seed,
parallel-safe, and traceable (symbolic executors thread keys explicitly).
"""
from __future__ import annotations

import threading

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        import jax
        _state.key = jax.random.PRNGKey(0)
        _state.count = 0


def seed(seed_state):
    """Parity with mx.random.seed (python/mxnet/random.py)."""
    import jax
    _ensure()
    _state.key = jax.random.PRNGKey(int(seed_state))
    _state.count = 0


def next_key():
    import jax
    _ensure()
    k = jax.random.fold_in(_state.key, _state.count)
    _state.count += 1
    return k


from contextlib import contextmanager


@contextmanager
def use_key(key):
    """Thread an explicit key (possibly a tracer) as the root for the scope.

    Used by traced/hybridized execution so random ops inside jit draw from a
    per-call key argument instead of baking host-side state into the trace.
    """
    _ensure()
    prev = (_state.key, _state.count)
    _state.key = key
    _state.count = 0
    try:
        yield
    finally:
        _state.key, _state.count = prev
