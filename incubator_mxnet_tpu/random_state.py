"""Global PRNG state for eager random ops.

The reference seeds per-device mshadow/Philox generators via
``mx.random.seed`` (src/resource.cc:160, src/common/random_generator.h).
TPU-natively we keep one root ``jax.random`` key and derive a fresh,
counter-folded subkey per eager random call — deterministic given the seed,
parallel-safe, and traceable (symbolic executors thread keys explicitly).
"""
from __future__ import annotations

import threading

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        import jax
        _state.key = jax.random.PRNGKey(0)
        _state.count = 0


def seed(seed_state):
    """Parity with mx.random.seed (python/mxnet/random.py)."""
    import jax
    _ensure()
    _state.key = jax.random.PRNGKey(int(seed_state))
    _state.count = 0


def get_state():
    """Snapshot the calling thread's PRNG state as plain host data —
    what the armor checkpoint serializes so a resumed run draws the same
    stream the dead one would have.  Handles both key flavors: typed
    (new-style) keys are unwrapped via ``jax.random.key_data``; raw
    uint32 keys pass through."""
    import numpy as np
    import jax
    _ensure()
    k = _state.key
    typed = False
    try:
        typed = jax.dtypes.issubdtype(k.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        pass
    raw = np.asarray(jax.random.key_data(k) if typed else k)
    return {"data": raw.tobytes(), "dtype": str(raw.dtype),
            "shape": tuple(raw.shape), "typed": typed,
            "count": _state.count}


def set_state(state):
    """Restore a :func:`get_state` snapshot onto the calling thread."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    raw = np.frombuffer(state["data"], dtype=np.dtype(state["dtype"]))
    raw = raw.reshape(state["shape"])
    key = jnp.asarray(raw)
    if state.get("typed"):
        key = jax.random.wrap_key_data(key)
    _state.key = key
    _state.count = int(state["count"])


def next_key():
    import jax
    _ensure()
    k = jax.random.fold_in(_state.key, _state.count)
    _state.count += 1
    return k


from contextlib import contextmanager


@contextmanager
def use_key(key):
    """Thread an explicit key (possibly a tracer) as the root for the scope.

    Used by traced/hybridized execution so random ops inside jit draw from a
    per-call key argument instead of baking host-side state into the trace.
    """
    _ensure()
    prev = (_state.key, _state.count)
    _state.key = key
    _state.count = 0
    try:
        yield
    finally:
        _state.key, _state.count = prev
