"""Legacy model API: checkpoint helpers + FeedForward.

ref: python/mxnet/model.py (995 LoC) — ``save_checkpoint``/``load_checkpoint``
(:366,396) write ``prefix-symbol.json`` + ``prefix-####.params``, the format
every MXNet deployment pipeline consumes; ``FeedForward`` is the deprecated
high-level trainer kept for script compatibility (it delegates to Module).
"""
from __future__ import annotations

import logging

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "resume_from_checkpoint", "FeedForward", "BatchEndParam"]

from .module.base_module import BatchEndParam  # re-export for parity


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """ref: model.py:366 save_checkpoint."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def _checkpoint_epochs(prefix):
    """Every epoch with a ``prefix-NNNN.params`` file, ascending."""
    import glob
    import re
    epochs = []
    for path in glob.glob(glob.escape(prefix) + "-*.params"):
        m = re.match(re.escape(prefix) + r"-(\d{4,})\.params$", path)
        if m:
            epochs.append(int(m.group(1)))
    return sorted(epochs)


def latest_checkpoint(prefix):
    """Highest epoch number with a ``prefix-NNNN.params`` file, or None.

    The recovery primitive the reference lacked (SURVEY §5.3: "no
    checkpoint-based auto-resume loop"): pair with
    :func:`resume_from_checkpoint` to restart training after a failure.
    """
    epochs = _checkpoint_epochs(prefix)
    return epochs[-1] if epochs else None


def resume_from_checkpoint(prefix):
    """(symbol, arg_params, aux_params, begin_epoch) from the newest
    LOADABLE checkpoint, or (None, None, None, 0) when none exists —
    feed straight into ``Module.fit(arg_params=..., begin_epoch=...)``
    for crash-safe restarts.

    Robustness contract (graftarmor): a corrupt or truncated newest
    checkpoint — a host killed mid-save under a pre-atomic writer, a
    half-copied file — is SKIPPED with a warning and the walk falls back
    to the next-older epoch, so resume lands on the last epoch whose
    bytes actually load.  (nd.save itself now publishes atomically via
    tmp+rename, so new checkpoints can no longer be torn; this guards
    files from other writers and other eras.)"""
    for epoch in reversed(_checkpoint_epochs(prefix)):
        try:
            symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        except Exception as exc:
            logging.warning(
                "checkpoint %s-%04d.params is not loadable (%r) — "
                "falling back to the previous epoch", prefix, epoch, exc)
            continue
        return symbol, arg_params, aux_params, epoch
    return None, None, None, 0


def load_checkpoint(prefix, epoch):
    """ref: model.py:396 load_checkpoint → (symbol, arg_params, aux_params)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return (symbol, arg_params, aux_params)


class FeedForward(object):
    """Deprecated high-level model (ref: model.py class FeedForward).
    Kept as a thin shim over Module for old scripts."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        import warnings
        warnings.warn("FeedForward is deprecated. Please use Module instead.",
                      DeprecationWarning, stacklevel=2)
        from . import initializer as init_mod
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer if initializer is not None \
            else init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    def _make_module(self, data_iter):
        from .module import Module
        label_names = [n for n in self.symbol.list_arguments()
                       if n.endswith("label")]
        data_names = [d.name for d in data_iter.provide_data]
        self._module = Module(self.symbol, data_names=data_names,
                              label_names=label_names, context=self.ctx)

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        """ref: model.py FeedForward.fit → Module.fit."""
        from .io import NDArrayIter
        if isinstance(X, (np.ndarray, nd.NDArray)):
            X = NDArrayIter(X, y, batch_size=self.numpy_batch_size)
        self._make_module(X)
        self._module.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, optimizer=self.optimizer,
                         optimizer_params=self.kwargs,
                         initializer=self.initializer,
                         arg_params=self.arg_params,
                         aux_params=self.aux_params,
                         begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch, monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        """ref: model.py FeedForward.predict."""
        from .io import NDArrayIter
        if isinstance(X, (np.ndarray, nd.NDArray)):
            X = NDArrayIter(X, batch_size=min(self.numpy_batch_size, len(X)))
        if self._module is None:
            self._make_module(X)
            self._module.bind(X.provide_data, X.provide_label,
                              for_training=False)
            self._module.set_params(self.arg_params, self.aux_params or {})
        out = self._module.predict(X, num_batch=num_batch, reset=reset)
        return out.asnumpy() if hasattr(out, "asnumpy") else out

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        """ref: model.py FeedForward.score:725 → Module.score."""
        from .io import NDArrayIter
        if isinstance(X, (np.ndarray, nd.NDArray)):
            raise TypeError("score requires a DataIter with labels")
        if self._module is None:
            self._make_module(X)
            self._module.bind(X.provide_data, X.provide_label,
                              for_training=False)
            self._module.set_params(self.arg_params, self.aux_params or {})
        res = self._module.score(X, eval_metric, num_batch=num_batch,
                                 batch_end_callback=batch_end_callback,
                                 reset=reset)
        return res[0][1] if res else None

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        """Train a model in one call (ref: model.py FeedForward.create:932)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model

    def save(self, prefix, epoch=None):
        """ref: model.py FeedForward.save."""
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        """ref: model.py FeedForward.load."""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
