"""Learning-rate schedules (ref: python/mxnet/lr_scheduler.py).

Same scheduler(num_update) → lr call contract as the reference's Factor /
MultiFactor / Poly schedulers, re-derived as *closed-form* functions of
``num_update``: the reference mutates ``base_lr`` in a while-loop, which
makes schedules history-dependent; computing the decay count directly
gives identical values for the monotonically-increasing ``num_update``
stream optimizers produce, and stays correct if a scheduler is probed
out of order (e.g. when resuming from a checkpoint).
"""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler(object):
    """Base: subclasses implement ``__call__(num_update) -> lr``
    (ref: lr_scheduler.py:24; consumed by Optimizer._get_lr)."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr
        self._last_logged = None

    def _log_if_changed(self, num_update, lr):
        if lr != self._last_logged:
            self._last_logged = lr
            logging.info("lr schedule: update %d -> %.5e", num_update, lr)

    def __call__(self, num_update):
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """Multiply by ``factor`` every ``step`` updates, floored at
    ``stop_factor_lr`` (ref: lr_scheduler.py FactorScheduler)."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def __call__(self, num_update):
        n_decays = max(0, (int(num_update) - 1) // self.step)
        lr = self.base_lr * self.factor ** n_decays
        lr = max(lr, self.stop_factor_lr)
        self._log_if_changed(num_update, lr)
        return lr


class MultiFactorScheduler(LRScheduler):
    """Multiply by ``factor`` at each milestone in ``step``
    (ref: lr_scheduler.py MultiFactorScheduler)."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty increasing list")
        if any(s < 1 for s in step) or \
                any(later <= earlier
                    for earlier, later in zip(step, step[1:])):
            raise ValueError("step must be an increasing list of ints >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step = list(step)
        self.factor = factor

    def __call__(self, num_update):
        n_decays = sum(1 for s in self.step if num_update > s)
        lr = self.base_lr * self.factor ** n_decays
        self._log_if_changed(num_update, lr)
        return lr


class PolyScheduler(LRScheduler):
    """base_lr · (1 - t/T)^power, zero after T updates
    (ref: lr_scheduler.py PolyScheduler)."""

    def __init__(self, max_update, base_lr=0.01, pwr=2):
        super().__init__(base_lr=base_lr)
        if int(max_update) < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = int(max_update)
        self.power = pwr

    def __call__(self, num_update):
        t = min(int(num_update), self.max_update)
        return self.base_lr * (1.0 - t / self.max_update) ** self.power


class CosineScheduler(LRScheduler):
    """Cosine decay from base_lr to final_lr over max_update, with an
    optional linear warmup — the modern large-batch default (no direct
    reference twin; LBSGD covers warmup in the reference)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0.0,
                 warmup_steps=0, warmup_begin_lr=0.0):
        super().__init__(base_lr=base_lr)
        self.max_update = int(max_update)
        self.final_lr = final_lr
        self.warmup_steps = int(warmup_steps)
        self.warmup_begin_lr = warmup_begin_lr

    def __call__(self, num_update):
        t = int(num_update)
        if t < self.warmup_steps:
            return self.warmup_begin_lr + (self.base_lr -
                                           self.warmup_begin_lr) * \
                t / max(1, self.warmup_steps)
        span = max(1, self.max_update - self.warmup_steps)
        frac = min(1.0, (t - self.warmup_steps) / span)
        return self.final_lr + 0.5 * (self.base_lr - self.final_lr) * \
            (1.0 + math.cos(math.pi * frac))
