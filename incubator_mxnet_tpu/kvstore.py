"""KVStore: key-value parameter synchronization.

TPU-native rebirth of src/kvstore/ + python/mxnet/kvstore.py:

* ``local`` / ``device`` — single-process multi-device reduce/broadcast
  (ref: kvstore_local.h:52, comm.h CommCPU/CommDevice).  On TPU the "device
  reduce" is an XLA all-reduce when arrays live on a mesh (parallel package);
  for per-context replica lists (Gluon Trainer, Module) it is a tree-sum in
  one fused XLA program.
* ``nccl`` maps to ``device`` — ICI collectives replace NCCL rings
  (ref: kvstore_nccl.h:62 → psum over ICI, SURVEY §2.4).
* ``dist_sync``/``dist_async`` — multi-host path built on jax.distributed
  (see parallel/dist.py); single-process fallback behaves like local with
  rank 0 of 1, so the same training scripts run anywhere.
* Gradient compression: 2-bit stochastic-threshold quantization with
  residual accumulation — same algebra as the reference
  (src/kvstore/gradient_compression.h:37-132), as an XLA kernel.
* ``set_optimizer`` runs the updater on the store (server-side optimizer,
  ref: kvstore_dist_server.h:145) — here the "server" is the store object.
"""
from __future__ import annotations

import os
import pickle
import sys
import time

import numpy as np
import jax.numpy as jnp

from .analysis import tsan as _tsan
from .armor import faults as _faults
from .base import MXNetError
from .ndarray import NDArray
from .ndarray import ndarray as _nd
from . import optimizer as opt
from .telemetry import blackbox as _blackbox
from .telemetry import lens as _lens
from .telemetry import metrics as _tmetrics


def _nd_bytes(arr):
    """Logical payload size from metadata only (never forces a flush)."""
    n = 1
    for s in arr.shape:
        n *= int(s)
    return n * np.dtype(arr.dtype).itemsize


class _NullCtx(object):
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CTX = _NullCtx()


def _xray_boundary(label):
    """graftxray program-boundary marker: when the capture harness is
    armed, wrap the host side of the reduce in a profiler
    ``TraceAnnotation`` so a capture shows exactly where program A ends
    and program B begins (host event — never enters phase attribution,
    which counts device ops only).  Unarmed cost: one memoized env
    read."""
    from .telemetry import xray as _xray
    if not _xray.armed():
        return _NULL_CTX
    try:
        import jax.profiler as _jprof
        return _jprof.TraceAnnotation("xray:kvstore:%s" % (label or "reduce"))
    except Exception:
        return _NULL_CTX


def _wire_bytes(nbytes, compressor):
    """Post-compression size of an ``nbytes`` payload on the wire: 2-bit
    quantization packs 16 elements per float32 word (ref:
    gradient_compression.h packing) — the single place this ratio lives,
    shared with the dist paths."""
    if compressor is None:
        return nbytes
    return max(nbytes // 16, 1)

__all__ = ["KVStore", "ReduceHandle", "PullHandle", "create",
           "create_kvstore"]


class _AsyncHandle(object):
    """Shared issue/wait split of the full-duplex wire (graftlap's
    reduces + graftduplex's pulls): the collective work is already
    dispatched at construction, ``values`` hold the in-flight results,
    and :meth:`wait` blocks until ready.  Between issue and wait the
    handle keeps an open flight-recorder bracket carrying the bucket
    label, so a collective that never lands is named by the watchdog
    and shows up in crash dumps as the stuck in-flight bucket.

    ``issued_at`` is the issue-time ``perf_counter()`` stamp — consumers
    derive the overlap ratio (fraction of in-flight wall time hidden
    under backward / the next forward) from it; :meth:`wait` records the
    split as ``blocked_s`` (host visibly waiting) vs ``inflight_s``
    (issue→wait-return, the upper bound on what was hidden)."""

    __slots__ = ("values", "label", "issued_at", "blocked_s", "inflight_s",
                 "_bracket", "_done", "__weakref__")

    def __init__(self, values, label=None, _bracket=None):
        self.values = list(values)
        self.label = label
        self.issued_at = time.perf_counter()
        self.blocked_s = 0.0
        self.inflight_s = 0.0
        self._bracket = _bracket
        self._done = False
        if _tsan._ACTIVE[0]:
            # grafttsan: the values are now in flight — issue is a
            # happens-before release; only wait() (the acquire) lets
            # another thread touch them (EH201 otherwise)
            _tsan.handle_issue(self)

    @property
    def done(self):
        return self._done

    def _close(self):
        if self._bracket is not None:
            bracket, self._bracket = self._bracket, None
            bracket.__exit__(None, None, None)

    def _begin_wait(self):
        """Flip the flight-recorder bracket from "deliberately left in
        flight" to "being waited on": re-stamp its clock and drop the
        ``async_pending`` flag so the watchdog starts aging it.  Before
        this, a long gap between issue and wait (a big backward, user
        code between backward and step, the next forward's early layers)
        is healthy overlap, not a hang — the watchdog must not trip on
        it."""
        entry = getattr(self._bracket, "entry", None)
        if entry is not None and entry.pop("async_pending", None):
            entry["since"] = time.time()

    def _materialize(self):
        """Hook for handles whose writes are deferred to wait time (the
        dist_async host parameter service: the pull RPC runs on a
        background thread and lands here)."""

    def wait(self):
        """Block until the in-flight values are ready; returns them.
        Idempotent — later calls are free.  graftlens books the blocked
        span as exposed communication and the issue→wait-return span as
        in-flight communication — an upper bound on the wire time the
        overlap hid (a handle whose wait queues behind earlier handles
        books their wait time too, the same convention as
        ``graft_trainer_overlap_ratio``)."""
        if not self._done:
            self._done = True
            if _tsan._ACTIVE[0]:
                # acquire the issue-time release: writes by the waiting
                # thread from here on (incl. _materialize's deferred
                # applies) are ordered after the issue.  The grafttsan
                # registry stays live until the blocking section below
                # returns — the wire owns the bytes until then, so a
                # third-thread write mid-wait is still an EH201 race
                _tsan.handle_acquire(self)
            self._begin_wait()
            t0 = time.perf_counter()
            try:
                # graftarmor chaos site: the wait side of every issued
                # collective (delay models a straggler; error a failed
                # wire) — injected BEFORE the block so the bracket
                # closes through the normal finally path
                _faults.fault_point("collective.wait", label=self.label,
                                    n_values=len(self.values))
                self._materialize()
                import jax
                jax.block_until_ready([v._read() for v in self.values])
            finally:
                t1 = time.perf_counter()
                self.blocked_s = t1 - t0
                self.inflight_s = t1 - self.issued_at
                if self.values:
                    # an empty handle never hit the wire: booking its
                    # issue->wait gap would fake hidden communication
                    _lens.comm(t0, t1, inflight=t1 - self.issued_at)
                self._close()
                _tsan.handle_settle(self)
        return self.values

    def abandon(self):
        """Drop the handle without consuming the result (the stale
        fallback).  Any dispatched work completes on its own; only the
        bracket closes and the values are never read."""
        self._done = True
        _tsan.handle_settle(self)   # no acquire edge: values unconsumed
        self._close()


class ReduceHandle(_AsyncHandle):
    """One asynchronously issued bucket reduce (graftlap) — see
    :class:`_AsyncHandle`; returned by :meth:`KVStore.reduce_many_async`
    with the reduce already on the wire (XLA dispatches asynchronously)."""

    __slots__ = ()


class PullHandle(_AsyncHandle):
    """One asynchronously issued weight pull/broadcast (graftduplex).

    Returned by :meth:`KVStore.pull_many_async`: the in-process stores
    rebind the out arrays at ISSUE time (each ``_write`` is an async XLA
    dispatch, so the bytes stream while the host moves on) and
    :meth:`wait` only blocks until they are ready; the dist_async host
    parameter service instead runs the pull RPC on a background thread
    and applies the fetched values at wait time, version-gated per out
    array (see ``DistKVStore.pull_many_async``).  Consumers (the
    ``overlap.PullScheduler``) wait at FIRST USE of any out array in the
    next forward, so updated weights ride under data loading and the
    early layers.  ``stale`` counts out arrays whose pulled value was
    dropped because the array was overwritten between issue and wait
    (the serial ordering — pull, then user write — is preserved)."""

    __slots__ = ("stale",)

    def __init__(self, values, label=None, _bracket=None):
        super().__init__(values, label=label, _bracket=_bracket)
        self.stale = 0


def _key_str(key):
    return str(key)


class _TwoBitCompressor(object):
    """2-bit gradient compression with residual (ref:
    src/kvstore/gradient_compression.h:37-132 — quantize_2bit kernel)."""

    def __init__(self, threshold=0.5):
        self.threshold = float(threshold)
        self.residuals = {}

    def compress(self, key, grad):
        t = self.threshold
        r = self.residuals.get(key)
        g = grad._read()
        if r is None:
            r = jnp.zeros_like(g)
        acc = r + g
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0)).astype(g.dtype)
        self.residuals[key] = acc - q
        return NDArray(q, ctx=grad._ctx)


class KVStore(object):
    """Single-process store (ref: include/mxnet/kvstore.h:47-382 API)."""

    def __init__(self, type_="local"):
        self._type = type_
        self._store = {}           # key -> NDArray (the "server" copy)
        self._updater = None
        self._compressor = None
        self._quant_override = None  # set_gradient_compression("2bit")
        #                              routes the BUCKET wire onto the
        #                              block-scaled quant path (graftzero)
        self._str_keys = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        """ref: kvstore.h get_rank — single-process is rank 0."""
        from .parallel import dist
        return dist.rank()

    @property
    def num_workers(self):
        from .parallel import dist
        return dist.num_workers()

    # -- data path ---------------------------------------------------------
    def init(self, key, value):
        """ref: KVStore::Init — one-time value registration."""
        keys, values = self._normalize(key, value)
        for k, vlist in zip(keys, values):
            if k in self._store:
                raise ValueError("duplicate init of key %s" % k)
            self._store[k] = vlist[0].copy()
            if _tsan._ACTIVE[0]:
                # grafttsan tracked cell per store value (EH204): the
                # store-side updater writes (push/apply_reduced) and
                # pull reads run through NDArray._write/_read, so an
                # unsynchronized cross-thread updater-write vs pull-read
                # on the shared "server" copy is named with both stacks
                _tsan.track(self._store[k],
                            label="%s._store[%s]" % (self._type, k))

    def push(self, key, value, priority=0):
        """Aggregate value(s) into the store (ref: KVStore::Push).

        Multi-device lists are reduced (CommCPU/CommDevice::Reduce); with an
        updater set, the update is applied store-side (server semantics).
        """
        keys, values = self._normalize(key, value)
        entries = []            # ordered (key, reduced) — keys may repeat
        raw_bytes = wire_bytes = 0
        for k, vlist in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            red = self._reduce(vlist)
            nb = _nd_bytes(red)
            raw_bytes += nb
            wire_bytes += _wire_bytes(nb, self._compressor)
            if self._compressor is not None:
                red = self._compressor.compress(k, red)
            entries.append((k, red))
        _tmetrics.kvstore_push(raw_bytes, wire_bytes)
        # one fused cross-worker collective for the whole push
        # (ref: big-array sharding amortization, kvstore_dist.h — here the
        # amortization is batching keys into a single allreduce); the
        # graftwatch bracket records it in the flight recorder and puts a
        # stalled allreduce in the watchdog's sights
        with _blackbox.collective("push", n_keys=len(entries),
                                  keys=[k for k, _ in entries[:4]],
                                  nbytes=raw_bytes, wire_bytes=wire_bytes):
            self._cross_worker_reduce_many([r for _, r in entries],
                                           compress=True)
        for k, red in entries:
            if self._updater is not None:
                self._updater(_int_key(k), red, self._store[k])
            else:
                # no updater: store holds the reduced value (ref:
                # kvstore_local.h PushImpl assigns local = merged)
                self._store[k]._write(red._read().astype(self._store[k].dtype))

    def _cross_worker_reduce_many(self, reds, heartbeat=True,
                                  compress=False):
        """Single-process store: nothing to do (dist overrides with one
        fused collective over all values; mutates them in place).
        ``heartbeat=False`` marks async issues: the dist path skips its
        piggybacked worker-heartbeat allreduce there, because reading the
        heartbeat result host-side would serialize against the bucket
        collective just dispatched — exactly the wait graftlap exists to
        avoid.  ``compress=True`` marks per-key PUSH traffic — the only
        wire the legacy 2-bit compressor may touch; bucket flats
        (``reduce_many*``) quantize through the block-scaled graftzero
        path instead and must never hit the per-key compressor's
        thresholding."""
        return reds

    def push_many(self, keys, values, priority=0):
        """Batched multi-key push: one call, one fused cross-worker
        collective for the whole key list (the batching contract of the
        reference's big-array sharding, kvstore_dist.h — here the
        amortization is key-batching).  ``push`` already accepts key
        lists; this spelling is the Trainer-facing API that guarantees
        the single-collective behavior."""
        return self.push(list(keys), list(values), priority=priority)

    def pull_many(self, keys, outs, priority=0):
        """Batched multi-key pull (companion of :meth:`push_many`)."""
        return self.pull(list(keys), outs, priority=priority)

    def reduce_many(self, values, label=None):
        """Reduce a list of dense NDArrays across workers IN PLACE with
        as few collectives as possible (one per dtype group on the dist
        wire) and return them.  This is the raw bucket wire the fused
        Trainer.step path rides: no per-key store bookkeeping, no
        server-side updater — just the allreduce.  Single-process stores
        have nothing to reduce, but the push/pull byte counters still
        observe the payload so fused vs per-param runs report comparable
        kvstore telemetry.  ``label`` names the flight-recorder bracket
        (graftstep tags its program-boundary reduce "compiled_step" so a
        hang between the fwd+bwd and update programs is attributable)."""
        if not values:
            return values
        raw = sum(_nd_bytes(v) for v in values)
        _tmetrics.kvstore_push(raw, raw)
        _tmetrics.kvstore_pull(raw)
        extra = {"label": label} if label else {}
        with _blackbox.collective("reduce_many", n_keys=len(values),
                                  nbytes=raw, **extra):
            with _xray_boundary(label):
                return self._cross_worker_reduce_many(list(values))

    def reduce_many_async(self, values, label=None):
        """Issue the cross-worker reduce of ``values`` WITHOUT waiting
        and return a :class:`ReduceHandle` (graftlap).  The collective is
        dispatched immediately — on the dist wire that is the in-graph
        XLA all-reduce, which executes asynchronously — so the caller
        (the Trainer's bucket scheduler, firing from a grad-ready hook
        mid-backward) keeps computing while the bytes move.  The handle's
        ``wait()`` is the only synchronization point; until then the
        reduce is an open flight-recorder bracket carrying ``label``, so
        the watchdog and crash dumps can name a stuck bucket.  Byte
        accounting and reduction algebra are EXACTLY ``reduce_many``'s
        (same per-value elementwise worker sum), only the wait moves."""
        values = list(values)
        if not values:
            return ReduceHandle(values, label=label)
        raw = sum(_nd_bytes(v) for v in values)
        _tmetrics.kvstore_push(raw, raw)
        _tmetrics.kvstore_pull(raw)
        bracket = _blackbox.collective(
            "reduce_many_async", n_keys=len(values), nbytes=raw,
            bucket=label)
        bracket.__enter__()
        entry = getattr(bracket, "entry", None)
        if entry is not None:
            # watchdog contract: an async bracket ages only from the
            # moment someone blocks on it (ReduceHandle._begin_wait) —
            # its open time before that measures healthy overlap
            entry["async_pending"] = True
        try:
            # graftarmor chaos site: the issue side of the async wire
            _faults.fault_point("collective.issue", label=label,
                                n_values=len(values))
            self._cross_worker_reduce_many(values, heartbeat=False)
        except BaseException:
            bracket.__exit__(*sys.exc_info())
            raise
        return ReduceHandle(values, label=label, _bracket=bracket)

    # -- graftzero: the block-scaled quantized bucket wire ------------------
    @staticmethod
    def _quant_signature(n_elems, mode, block):
        """The wire signature the lockstep auditor folds: mode, block
        size, total block count and quantized byte count.  A rank that
        disagrees on ``GRAFT_QUANT_REDUCE``/``GRAFT_QUANT_BLOCK`` folds
        a different digest and is NAMED by the heartbeat cross-check
        before the mispaired collective hangs the wire."""
        from .parallel import quant as _quant
        nb = sum(_quant.n_blocks(n, block) for n in n_elems)
        wire = sum(_quant.wire_nbytes(n, mode, block) for n in n_elems)
        return wire, "q:%s:b%d:nb%d" % (mode, int(block), nb)

    def reduce_quantized(self, payloads, n_elems, mode, block, label=None):
        """Reduce a batch of quantized bucket payloads across workers IN
        PLACE — the graftzero twin of :meth:`reduce_many`.  ``payloads``
        is ``[(codes, scales)]`` NDArray pairs (one per bucket, from
        ``parallel.quant.encode``), ``n_elems`` the per-bucket element
        counts.  Byte accounting: raw = the f32 bytes the wire replaces,
        wire = packed codes + scales (the compression-ratio gauge reads
        the bandwidth saving straight off these).  The whole batch is
        one flight-recorder bracket whose identity folds the quant
        signature (lockstep contract)."""
        if not payloads:
            return payloads
        raw = 4 * sum(int(n) for n in n_elems)
        wire, sig = self._quant_signature(n_elems, mode, block)
        _tmetrics.kvstore_push(raw, wire)
        _tmetrics.kvstore_pull(wire)
        extra = {"label": label} if label else {}
        with _blackbox.collective("reduce_quant", n_keys=len(payloads),
                                  nbytes=wire, keys=[sig], **extra):
            with _xray_boundary(label):
                self._cross_worker_reduce_quantized(
                    list(payloads), list(n_elems), mode, block)
        return payloads

    def reduce_quantized_async(self, payloads, n_elems, mode, block,
                               label=None):
        """Issue the quantized payload reduce WITHOUT waiting — the
        graftzero twin of :meth:`reduce_many_async`, same bracket /
        watchdog / fault-point contract, quantized byte accounting."""
        payloads = list(payloads)
        flat_vals = [a for pair in payloads for a in pair]
        if not payloads:
            return ReduceHandle(flat_vals, label=label)
        raw = 4 * sum(int(n) for n in n_elems)
        wire, sig = self._quant_signature(n_elems, mode, block)
        _tmetrics.kvstore_push(raw, wire)
        _tmetrics.kvstore_pull(wire)
        bracket = _blackbox.collective(
            "reduce_quant_async", n_keys=len(payloads), nbytes=wire,
            keys=[sig], bucket=label)
        bracket.__enter__()
        entry = getattr(bracket, "entry", None)
        if entry is not None:
            entry["async_pending"] = True
        try:
            _faults.fault_point("collective.issue", label=label,
                                n_values=len(payloads))
            self._cross_worker_reduce_quantized(
                payloads, list(n_elems), mode, block, heartbeat=False)
        except BaseException:
            bracket.__exit__(*sys.exc_info())
            raise
        return ReduceHandle(flat_vals, label=label, _bracket=bracket)

    def _cross_worker_reduce_quantized(self, payloads, n_elems, mode,
                                       block, heartbeat=True):
        """Single-process store: the payload already IS the sum (one
        worker) — nothing moves.  The dist store overrides with the
        EQuARX-style quantized reduce-scatter + all-gather
        (``parallel.quant.reduce_payload_sum``), mutating the payload
        NDArrays in place."""
        return payloads

    def heartbeat(self):
        """Run one dist worker heartbeat outside a reduce batch.  The
        heartbeat normally piggybacks on ``_cross_worker_reduce_many``,
        but a fully-overlapped step (graftlap) reduces exclusively
        through ``reduce_many_async`` — which must skip it (the host-side
        read would serialize the async dispatch) — so the Trainer calls
        this once from the wait side instead, keeping the worker-skew
        histogram and the crash-dump last-seen table live.  Single-process
        stores have no peers: no-op (dist overrides)."""
        return None

    def apply_reduced(self, keys, values):
        """Apply ALREADY cross-worker-reduced gradients to the store —
        the update_on_kvstore leg of the full-duplex step (graftduplex).

        The duplex Trainer/Module path reduces a whole bucket as one
        concatenated buffer (``reduce_many`` / ``reduce_many_async``),
        splits it, and hands the per-key pieces here: each key gets the
        store-side updater tick (server semantics, exactly what ``push``
        would have run) or a plain assignment when no updater is set —
        but NO second reduction and no extra collective.  Key order is
        the caller's bucket order; per-key updates are independent, so
        the result is bit-identical to the per-key ``push`` path."""
        keys, vals = self._normalize(list(keys), list(values))
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError("key %s has not been initialized" % k)
            red = vlist[0]
            if self._updater is not None:
                self._updater(_int_key(k), red, self._store[k])
            else:
                from . import engine as _engine
                tgt = self._store[k]
                tgt._write(_engine.colocate(
                    red._read().astype(tgt.dtype), tgt._read()))

    def pull_many_async(self, keys, outs, priority=0, label=None):
        """Issue a batched multi-key pull WITHOUT waiting and return a
        :class:`PullHandle` (graftduplex — the pull-side mirror of
        :meth:`reduce_many_async`).

        For the in-process stores the broadcast writes happen NOW — each
        out array rebinds to the store value through an async XLA
        dispatch, so the bytes stream back while the host runs data
        loading and the next forward's early layers — and the handle's
        ``wait()`` (fired by the consumer's first-touch weight hooks, or
        at the latest at the start of the next step) is the only
        synchronization point.  Until then the pull is an open
        flight-recorder bracket carrying ``label``, so the watchdog and
        crash dumps can name a stuck in-flight pull bucket.  Byte
        accounting matches :meth:`pull` exactly; only the wait moves.
        The dist_async parameter service overrides this with a
        background-thread RPC + version-gated wait-time writes."""
        keys, outs_n = self._normalize(list(keys), outs)
        flat_outs = [o for olist in outs_n for o in olist]
        nbytes = sum(_nd_bytes(o) for o in flat_outs)
        bracket = _blackbox.collective(
            "pull_many_async", n_keys=len(keys), keys=keys[:4],
            nbytes=nbytes, bucket=label)
        bracket.__enter__()
        entry = getattr(bracket, "entry", None)
        if entry is not None:
            # watchdog contract (same as reduce_many_async): an async
            # bracket ages only once someone blocks on it
            entry["async_pending"] = True
        try:
            from . import engine as _engine
            for k, olist in zip(keys, outs_n):
                if k not in self._store:
                    raise MXNetError("key %s has not been initialized" % k)
                val = self._store[k]._read()
                src_dtype = np.dtype(val.dtype)
                for o in olist:
                    v = val if np.dtype(o.dtype) == src_dtype \
                        else val.astype(o.dtype)
                    o._write(_engine.colocate(v, o._read()))
        except BaseException:
            bracket.__exit__(*sys.exc_info())
            raise
        _tmetrics.kvstore_pull(nbytes)
        return PullHandle(flat_outs, label=label, _bracket=bracket)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Broadcast store value into out list (ref: KVStore::Pull)."""
        assert out is not None
        keys, outs = self._normalize(key, out)
        # one metadata pass sizes the payload for both the flight
        # recorder and the byte counter (every write below either lands
        # or raises, so the up-front sum IS the pulled total)
        nbytes = sum(_nd_bytes(o) for olist in outs for o in olist)
        from . import engine as _engine
        with _blackbox.collective("pull", n_keys=len(keys), keys=keys[:4],
                                  nbytes=nbytes):
            for k, olist in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError("key %s has not been initialized" % k)
                # hoist the store read out of the replica loop, and skip
                # the astype copy when dtypes already match — the common
                # Trainer pull (grad -> grad, same dtype) is a pure rebind.
                # colocate: a multi-context replica list commits each out
                # to its own device; the broadcast must land there
                val = self._store[k]._read()
                src_dtype = np.dtype(val.dtype)
                for o in olist:
                    v = val if np.dtype(o.dtype) == src_dtype \
                        else val.astype(o.dtype)
                    o._write(_engine.colocate(v, o._read()))
        _tmetrics.kvstore_pull(nbytes)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only selected rows (ref: KVStore::PullRowSparse,
        kvstore_local.h PullRowSparseImpl)."""
        assert out is not None and row_ids is not None
        keys, outs = self._normalize(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(outs[0])
        from .ndarray.sparse import RowSparseNDArray
        for k, olist in zip(keys, outs):
            src = self._store[k]._read()
            for o, rid in zip(olist, row_ids):
                # dedup + sort row ids (PullRowSparseImpl contract)
                idx = jnp.asarray(np.unique(np.asarray(rid._read()))
                                  .astype(np.int32))
                rows = jnp.take(src, idx, axis=0)
                if isinstance(o, RowSparseNDArray):
                    # true row-sparse pull: only the requested rows
                    # materialize — O(|row_ids|) memory like the
                    # reference's PullRowSparseImpl (kvstore_local.h)
                    o.data = NDArray(rows.astype(o.data.dtype))
                    o.indices = NDArray(idx.astype(o.indices.dtype))
                else:
                    dense = jnp.zeros(o.shape, o._read().dtype)
                    dense = dense.at[idx].set(rows.astype(o._read().dtype))
                    o._write(dense)

    # -- reductions --------------------------------------------------------
    @staticmethod
    def _reduce(vlist):
        from .ndarray.sparse import BaseSparseNDArray, add_n
        if len(vlist) == 1:
            return vlist[0]
        if any(isinstance(v, BaseSparseNDArray) for v in vlist):
            # sparse-aware tree sum (ref: comm.h CommCPU ReduceRowSparse)
            return add_n(*vlist)
        from . import engine as _engine
        acc = vlist[0]._read()
        for v in vlist[1:]:
            # replicas committed to distinct devices (multi-ctx lists)
            # must be moved before the tree-sum — transfers preserve bits
            acc = acc + _engine.colocate(v._read(), acc)
        return NDArray(acc, ctx=vlist[0]._ctx)

    @staticmethod
    def _normalize(key, value):
        single = not isinstance(key, (list, tuple))
        keys = [key] if single else list(key)
        if single:
            values = [value if isinstance(value, (list, tuple)) else [value]]
        else:
            values = [v if isinstance(v, (list, tuple)) else [v] for v in value]
        return [_key_str(k) for k in keys], values

    # -- optimizer / updater ----------------------------------------------
    def set_updater(self, updater):
        """ref: kvstore.py _set_updater / KVStoreSetUpdater."""
        self._updater = updater

    def set_optimizer(self, optimizer):
        """ref: kvstore.py set_optimizer — the local store shares the live
        optimizer object (so Trainer's per-step rescale_grad / lr mutations
        apply); only the dist path pickles it to servers
        (kvstore_dist_server.h kController command channel)."""
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """ref: kvstore.py set_gradient_compression (2bit only, like ref).

        DEPRECATED for the Trainer step: the threshold compressor only
        ever rode the per-key serial wire (``push``), and forcing the
        step onto that wire defeated the bucket schedulers.  Calling
        this now routes ``Trainer.step``'s BUCKET reduces onto the
        block-scaled quantized wire (graftzero, ``GRAFT_QUANT_REDUCE``
        semantics with mode ``2bit``) while the per-key ``push`` API
        keeps the exact legacy threshold algebra.  ``GRAFT_QUANT_REDUCE=0``
        is the bit-identical escape hatch: it disables the bucket-wire
        quantization entirely (the env var always wins)."""
        ctype = compression_params.get("type", "2bit")
        if ctype != "2bit":
            raise ValueError("Unsupported type of gradient compression: %s" % ctype)
        import warnings
        warnings.warn(
            "set_gradient_compression is deprecated for the bucketed "
            "Trainer step: bucket reduces now ride the block-scaled "
            "quantized wire (GRAFT_QUANT_REDUCE=2bit semantics); the "
            "per-key push API keeps the legacy threshold algebra. Set "
            "GRAFT_QUANT_REDUCE=0 for the bit-identical escape hatch.",
            DeprecationWarning, stacklevel=2)
        self._compressor = _TwoBitCompressor(
            compression_params.get("threshold", 0.5))
        self._quant_override = "2bit"

    # -- distributed-only API (graceful single-process behavior) -----------
    def barrier(self):
        from .parallel import dist
        dist.barrier()

    def quiesce(self, timeout=None):
        """Drain every in-flight async operation this store owns
        (graftelastic: the mandatory prelude to a membership
        re-partition — key ranges must not move under live traffic).
        The local store issues nothing asynchronous on its own behalf,
        so the base is a no-op; ``DistKVStore`` overrides with the real
        drain and a typed ``QuiesceTimeoutError``."""
        return 0

    def send_command_to_servers(self, head, body):
        return

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _int_key(k):
    try:
        return int(k)
    except ValueError:
        return k


def create(name="local"):
    """Factory (ref: kvstore.cc:40-77 KVStore::Create by type string)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore("device" if name in ("device", "nccl") else "local")
    if name in ("dist_sync", "dist_async", "dist_device_sync"):
        from .parallel import dist
        return dist.DistKVStore(name)
    raise ValueError("Unknown KVStore type %s" % name)


def create_kvstore(kvstore, num_device, arg_params):
    """Resolve a kvstore spec into (store, update_on_kvstore)
    (ref: python/mxnet/model.py _create_kvstore, including the
    MXNET_UPDATE_ON_KVSTORE env override — 0 keeps the update local,
    which is also the switch that routes Module onto the bucketed
    fused/overlapped reduce path, graftduplex)."""
    try:
        update_on_kvstore = bool(int(
            os.environ.get("MXNET_UPDATE_ON_KVSTORE", "1")))
    except ValueError:
        update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = create(kvstore)
            if kvstore == "local":
                max_size = max(np.prod(param.shape) for param in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore
