"""Weight initializers (ref: python/mxnet/initializer.py).

Same registry + ``InitDesc``-driven dispatch as the reference: an
``Initializer`` is called with a named descriptor and fills the array,
routing ``_weight``/``_bias``/``_gamma``/``_beta``/``_mean``/``_var`` suffixes
to the right default fill, honoring ``__init__`` attr overrides, and
supporting serialization via ``dumps`` (optimizer-to-server parity).
All randomness flows through the framework PRNG (jax.random keys), not
global numpy state, so init is reproducible per `mx.random.seed`.
"""
from __future__ import annotations

import json
import logging
import re

import numpy as np
import jax
import jax.numpy as jnp

from . import random_state
from .ndarray import NDArray

__all__ = ["InitDesc", "Initializer", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Mixed", "Load", "register", "create"]

_INIT_REGISTRY = {}


def register(klass):
    """ref: initializer.py register decorator (mx.init.register)."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _INIT_REGISTRY[name.lower()](**kwargs)


class InitDesc(str):
    """Name + attrs descriptor (ref: initializer.py class InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer(object):
    """Base initializer with suffix dispatch (ref: initializer.py:95)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        if print_func is None:
            def asum_stat(x):
                return str((np.abs(x.asnumpy()).mean(),))
            print_func = asum_stat
        self._print_func = print_func
        return self

    def _verbose_print(self, desc, init, arr):
        if self._verbose and self._print_func:
            logging.info("Initialized %s as %s: %s", desc, init, self._print_func(arr))

    def dumps(self):
        """ref: initializer.py dumps — json [name, kwargs]."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
            self._verbose_print(desc, init, arr)
            return
        # suffix dispatch, parity with initializer.py __call__
        if desc.endswith("weight"):
            self._init_weight(desc, arr)
            self._verbose_print(desc, "weight", arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
            self._verbose_print(desc, "bias", arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
            self._verbose_print(desc, "gamma", arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
            self._verbose_print(desc, "beta", arr)
        elif desc.endswith("min"):
            self._init_zero(desc, arr)
        elif desc.endswith("max"):
            self._init_one(desc, arr)
        elif desc.endswith("running_mean") or desc.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif desc.endswith("running_var") or desc.endswith("moving_var"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_bias(self, _, arr):
        self._fill(arr, jnp.zeros(arr.shape, arr.dtype))

    def _init_gamma(self, _, arr):
        self._fill(arr, jnp.ones(arr.shape, arr.dtype))

    def _init_beta(self, _, arr):
        self._fill(arr, jnp.zeros(arr.shape, arr.dtype))

    def _init_zero(self, _, arr):
        self._fill(arr, jnp.zeros(arr.shape, arr.dtype))

    def _init_one(self, _, arr):
        self._fill(arr, jnp.ones(arr.shape, arr.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization is now "
            "limited to \"weight\", \"bias\", \"gamma\" (1.0), and \"beta\" (0.0). "
            "Please use mx.sym.Variable(init=mx.init.*) to set initialization "
            "pattern" % name)

    @staticmethod
    def _fill(arr, value):
        arr._write(jnp.asarray(value, arr._read().dtype))


@register
class Zero(Initializer):
    """ref: initializer.py class Zero (alias 'zeros')."""

    def _init_weight(self, _, arr):
        self._fill(arr, jnp.zeros(arr.shape, arr.dtype))


@register
class One(Initializer):
    """ref: initializer.py class One (alias 'ones')."""

    def _init_weight(self, _, arr):
        self._fill(arr, jnp.ones(arr.shape, arr.dtype))


_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._fill(arr, jnp.full(arr.shape, self.value, arr.dtype))


@register
class Uniform(Initializer):
    """U(-scale, scale) (ref: initializer.py class Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        k = random_state.next_key()
        self._fill(arr, jax.random.uniform(k, arr.shape, jnp.float32,
                                           -self.scale, self.scale))


@register
class Normal(Initializer):
    """N(0, sigma) (ref: initializer.py class Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        k = random_state.next_key()
        self._fill(arr, jax.random.normal(k, arr.shape, jnp.float32) * self.sigma)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (ref: initializer.py class Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        k = random_state.next_key()
        if self.rand_type == "uniform":
            tmp = jax.random.uniform(k, (nout, nin), jnp.float32, -1.0, 1.0)
        else:
            tmp = jax.random.normal(k, (nout, nin), jnp.float32)
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._fill(arr, (self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot (ref: initializer.py class Xavier — rnd_type
    uniform|gaussian, factor_type avg|in|out)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier initializer cannot be applied to vector %s. "
                             "It requires at least 2D." % name)
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factor)
        k = random_state.next_key()
        if self.rnd_type == "uniform":
            self._fill(arr, jax.random.uniform(k, shape, jnp.float32, -scale, scale))
        elif self.rnd_type == "gaussian":
            self._fill(arr, jax.random.normal(k, shape, jnp.float32) * scale)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming-He init for PReLU nets (ref: initializer.py class MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__(rnd_type="gaussian", factor_type=factor_type,
                         magnitude=magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (ref: initializer.py class Bilinear)."""

    def _init_weight(self, _, arr):
        weight = np.zeros(int(np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._fill(arr, jnp.asarray(weight.reshape(shape)))


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (ref: initializer.py class LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype="float32")
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden:2 * num_hidden] = self.forget_bias
        self._fill(arr, jnp.asarray(b))

    # names end in "_bias"; route to the same fill (the reference reaches this
    # class only via the __init__-attr path, which calls _init_weight directly)
    _init_bias = _init_weight


class Load(object):
    """Init from a dict of arrays with fallback (ref: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        qualified_param_name = re.compile("^(arg:|aux:)")
        self.param = {qualified_param_name.sub("", name): arr
                      for name, arr in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            assert tuple(arr.shape) == tuple(src.shape), \
                "Parameter %s cannot be initialized from loading. " % name + \
                "Shape mismatch, target %s vs loaded %s" % (str(arr.shape), str(src.shape))
            arr._write(jnp.asarray(src.asnumpy() if isinstance(src, NDArray) else src))
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            assert self.default_init is not None, \
                "Cannot Initialize %s. Not found in loaded param " % name + \
                "and no default Initializer is provided."
            self.default_init(name, arr)


class Mixed(object):
    """Pattern-matched mixture of initializers (ref: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            'Parameter name %s did not match any pattern. Consider adding a '
            '".*" pattern at the and with default Initializer.' % name)
