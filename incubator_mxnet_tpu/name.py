"""Automatic naming of layers/symbols (ref: python/mxnet/name.py).

``NameManager`` hands out ``dense0``, ``conv1``-style unique names; ``Prefix``
prepends a fixed prefix. Gluon's ``_BlockScope`` and Symbol creation both
consult the current manager, exactly as the reference does.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]

_current = threading.local()


class NameManager(object):
    """ref: name.py class NameManager."""

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if not hasattr(_current, "value"):
            _current.value = NameManager()
        self._old_manager = _current.value
        _current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager
        _current.value = self._old_manager

    @staticmethod
    def current():
        if not hasattr(_current, "value"):
            _current.value = NameManager()
        return _current.value


class Prefix(NameManager):
    """ref: name.py class Prefix."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
