"""AttrScope: scoped symbol attributes (ref: python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='dev1'):`` attaches attributes to every Symbol
created inside — the mechanism behind `ctx_group` model parallelism
(example/model-parallel/lstm/lstm.py:65; PlaceDevice pass
src/executor/graph_executor.cc:406). On TPU, ctx_group attrs translate to
sharding annotations (parallel package) rather than device copies.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_current = threading.local()


class AttrScope(object):
    """ref: attribute.py class AttrScope."""

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs

    def get(self, attr):
        """Merge scope attrs with user attrs (ref: attribute.py get)."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        if not hasattr(_current, "value"):
            _current.value = AttrScope()
        self._old_scope = _current.value
        attr = _current.value._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        _current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        _current.value = self._old_scope


def current():
    if not hasattr(_current, "value"):
        _current.value = AttrScope()
    return _current.value
