"""Runtime kernel compilation (ref: python/mxnet/rtc.py + src/common/rtc.cc).

The reference's ``CudaModule`` NVRTC-compiles CUDA C at runtime and
launches kernels on NDArrays.  The TPU-native equivalent of "user writes
a kernel, framework compiles it at runtime" is Pallas: ``PallasModule``
wraps user kernel functions, ``get_kernel().launch(...)`` places the
pallas_call and hands NDArrays through — same module/kernel/launch
shape as the reference API, with grid dims playing the same role.

On non-TPU backends the kernel runs through Pallas interpret mode, so
kernels remain testable on the CPU mesh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]


class PallasModule(object):
    """A collection of runtime-compiled kernels
    (ref: rtc.py CudaModule:42 — source string → module; here the
    "source" is a dict of Python Pallas kernel functions)."""

    def __init__(self, kernels, exports=()):
        if not isinstance(kernels, dict) or not kernels:
            raise MXNetError("PallasModule takes {name: kernel_fn}")
        self._kernels = dict(kernels)
        self.exports = tuple(exports) or tuple(kernels)
        missing = [n for n in self.exports if n not in self._kernels]
        if missing:
            raise MXNetError("exports %s name no kernel (have %s)"
                             % (missing, sorted(self._kernels)))

    def get_kernel(self, name, out_shape=None, out_dtype=None):
        """Look up an exported kernel (ref: rtc.py get_kernel:112).
        ``out_shape``/``out_dtype``: output spec; defaults to the first
        input's at launch."""
        if name not in self.exports:
            raise MXNetError("kernel %r is not exported (exports: %s)"
                             % (name, sorted(self.exports)))
        return PallasKernel(name, self._kernels[name], out_shape, out_dtype)


class PallasKernel(object):
    """One launchable kernel (ref: rtc.py CudaKernel:173)."""

    def __init__(self, name, fn, out_shape=None, out_dtype=None):
        self.name = name
        self._fn = fn
        self._out_shape = out_shape
        self._out_dtype = out_dtype
        self._compiled = {}

    def launch(self, args, ctx=None, grid_dims=(1,), block_dims=None,
               shared_mem=0):
        """Run the kernel over NDArray args; returns the output NDArray
        (ref: rtc.py CudaKernel.launch:185 — grid_dims maps to the Pallas
        grid; block_dims/shared_mem are CUDA-isms the TPU compiler owns).
        """
        from jax.experimental import pallas as pl

        vals = [a._read() if isinstance(a, NDArray) else jnp.asarray(a)
                for a in args]
        if ctx is not None:
            dev = ctx.jax_device()
            vals = [jax.device_put(v, dev) for v in vals]
        if any(int(g) < 1 for g in grid_dims):
            raise MXNetError("grid_dims must be positive, got %r"
                             % (grid_dims,))
        # keep the full grid rank: size-1 dims still own a program_id axis
        grid = tuple(int(g) for g in grid_dims) or (1,)
        out_shape = (tuple(self._out_shape) if self._out_shape is not None
                     else tuple(vals[0].shape))
        out_dtype = (self._out_dtype if self._out_dtype is not None
                     else vals[0].dtype)
        key = (tuple(v.shape for v in vals), tuple(str(v.dtype)
                                                   for v in vals), grid)
        call = self._compiled.get(key)
        if call is None:
            interpret = jax.default_backend() != "tpu"
            call = jax.jit(pl.pallas_call(
                self._fn, grid=grid,
                out_shape=jax.ShapeDtypeStruct(out_shape, out_dtype),
                interpret=interpret))
            self._compiled[key] = call
        return NDArray(call(*vals), ctx=ctx)   # ctx=None → current context


def CudaModule(*args, **kwargs):  # noqa: N802 - reference name
    """The reference entry point: CUDA source cannot target a TPU.
    Raises with a pointer at PallasModule (the rtc capability here)."""
    raise MXNetError(
        "CudaModule compiles CUDA C, which has no TPU target. Use "
        "mx.rtc.PallasModule with Pallas kernel functions — the runtime "
        "kernel-compilation path on this backend.")
