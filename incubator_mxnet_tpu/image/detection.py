"""Detection data pipeline: augmenters + ImageDetIter.

TPU-native rebirth of python/mxnet/image/detection.py (and the C++
src/io/image_det_aug_default.cc fast path): bounding-box-aware
augmentation — constrained random crop, random expand/pad, flips — plus
``ImageDetIter`` producing padded (batch, max_objects, 5+) labels for SSD
training (BASELINE config 4).

Labels flow as numpy (n_objects, 5+) rows ``[cls, xmin, ymin, xmax, ymax,
...]`` with corner coords normalized to [0, 1]; batches pad with -1 rows
(the convention MultiBoxTarget consumes).
"""
from __future__ import annotations

import json
import logging
import random as pyrandom

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from .image import (Augmenter, CreateAugmenter, ImageIter, fixed_crop,
                    imdecode, imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


def _box_areas(boxes):
    """Areas of (n, 4) corner boxes, clipped at zero."""
    return (np.maximum(0, boxes[:, 2] - boxes[:, 0])
            * np.maximum(0, boxes[:, 3] - boxes[:, 1]))


class DetAugmenter(object):
    """Base detection augmenter: __call__(src, label) → (src, label)
    (ref: detection.py DetAugmenter:39)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection pipeline; the label
    passes through (ref: detection.py DetBorrowAug:65)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("DetBorrowAug requires an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Apply one randomly chosen augmenter (or none, with ``skip_prob``)
    (ref: detection.py DetRandomSelectAug:90)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates together with probability p
    (ref: detection.py DetHorizontalFlipAug:126)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = src[:, ::-1]
            out = label.copy()
            out[:, 1] = 1.0 - label[:, 3]
            out[:, 3] = 1.0 - label[:, 1]
            label = out
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop: sampled windows must cover at least
    ``min_object_covered`` of some object; surviving boxes are re-mapped
    into the crop and dropped below ``min_eject_coverage``
    (ref: detection.py DetRandomCropAug:152)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def _remap_labels(self, label, x, y, w, h, H, W):
        """Re-express labels inside crop (x, y, w, h) pixels; None if no
        box survives the eject threshold."""
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - x / W) * (W / w)
        out[:, (2, 4)] = (out[:, (2, 4)] - y / H) * (H / h)
        clipped = out.copy()
        clipped[:, 1:5] = np.clip(out[:, 1:5], 0.0, 1.0)
        orig_areas = _box_areas(label[:, 1:5])
        new_areas = _box_areas(clipped[:, 1:5]) * (w * h) / (W * H)
        with np.errstate(divide="ignore", invalid="ignore"):
            coverage = np.where(orig_areas > 0, new_areas / orig_areas, 0.0)
        valid = ((clipped[:, 3] > clipped[:, 1])
                 & (clipped[:, 4] > clipped[:, 2])
                 & (coverage > self.min_eject_coverage))
        if not valid.any():
            return None
        return clipped[valid]

    def __call__(self, src, label):
        H, W = src.shape[0], src.shape[1]
        if not self.enabled or H <= 0 or W <= 0:
            return src, label
        boxes = label[:, 1:5]
        areas = _box_areas(boxes)
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range) * H * W
            h = int(round(np.sqrt(area / ratio)))
            w = int(round(h * ratio))
            if h <= 0 or w <= 0 or h > H or w > W:
                continue
            y = pyrandom.randint(0, H - h)
            x = pyrandom.randint(0, W - w)
            # min_object_covered: some valid object keeps enough area
            ix1 = np.maximum(boxes[:, 0], x / W)
            iy1 = np.maximum(boxes[:, 1], y / H)
            ix2 = np.minimum(boxes[:, 2], (x + w) / W)
            iy2 = np.minimum(boxes[:, 3], (y + h) / H)
            inter = (np.maximum(0, ix2 - ix1) * np.maximum(0, iy2 - iy1))
            with np.errstate(divide="ignore", invalid="ignore"):
                cover = np.where(areas > 0, inter / areas, 0.0)
            cover = cover[cover > 0]
            if cover.size == 0 or cover.min() <= self.min_object_covered:
                continue
            new_label = self._remap_labels(label, x, y, w, h, H, W)
            if new_label is not None:
                return fixed_crop(src, x, y, w, h, None), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expansion: place the image inside a larger canvas filled
    with ``pad_val`` and shrink the labels accordingly
    (ref: detection.py DetRandomPadAug:324)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val
        self.enabled = (area_range[1] >= 1.0
                        and 0 < aspect_ratio_range[0] <= aspect_ratio_range[1])

    def __call__(self, src, label):
        H, W = src.shape[0], src.shape[1]
        if not self.enabled or H <= 0 or W <= 0:
            return src, label
        arr = src.asnumpy() if hasattr(src, "asnumpy") else np.asarray(src)
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            area = pyrandom.uniform(*self.area_range) * H * W
            nh = int(round(np.sqrt(area / ratio)))
            nw = int(round(nh * ratio))
            if nh < H or nw < W:
                continue
            y = pyrandom.randint(0, nh - H)
            x = pyrandom.randint(0, nw - W)
            canvas = np.empty((nh, nw, arr.shape[2]), arr.dtype)
            canvas[:] = np.asarray(self.pad_val, arr.dtype)
            canvas[y:y + H, x:x + W] = arr
            out = label.copy()
            out[:, (1, 3)] = (out[:, (1, 3)] * W + x) / nw
            out[:, (2, 4)] = (out[:, (2, 4)] * H + y) / nh
            return nd.array(canvas, dtype=arr.dtype), out
        return src, label


class _DetResizeAug(DetAugmenter):
    """Force resize to (w, h); normalized labels are untouched."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        return imresize(src, self.size[0], self.size[1],
                        self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter pipeline
    (ref: detection.py CreateDetAugmenter:483).  ``rand_crop``/``rand_pad``
    are probabilities of applying the constrained crop / expansion."""
    auglist = []
    if resize > 0:
        auglist.append(_DetResizeAug((resize, resize), inter_method))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    # photometric augs borrowed from the classification pipeline
    from .image import (ColorJitterAug, HueJitterAug, LightingAug,
                        RandomGrayAug)
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    auglist.append(_DetResizeAug((data_shape[2], data_shape[1]),
                                 inter_method))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        from .image import ColorNormalizeAug
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: ImageIter + object labels padded to a fixed
    (max_objects, label_width) block per image
    (ref: detection.py ImageDetIter:625)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.label_shape = self._estimate_label_shape()
        self._provide_label = [io_mod.DataDesc(
            label_name, (self.batch_size,) + self.label_shape, "float32")]

    @staticmethod
    def _parse_label(label):
        """Raw .lst/.rec label → (n_objects, obj_width) array.  Format:
        [header_width, obj_width, <header...>, obj0..., obj1...]
        (ref: detection.py _parse_label)."""
        raw = np.asarray(label, np.float32).ravel()
        if raw.size < 7:
            raise RuntimeError("Label is too short for detection: %s"
                               % (raw,))
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5 or (raw.size - header_width) % obj_width != 0:
            raise RuntimeError("Label shape %s inconsistent with object "
                               "width %d" % (raw.shape, obj_width))
        out = raw[header_width:].reshape(-1, obj_width)
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        if not valid.any():
            raise RuntimeError("Sample with no valid label")
        return out[valid]

    def _estimate_label_shape(self):
        """Max object count over the dataset (one cheap pass)."""
        max_count, width = 0, 5
        self.reset()
        try:
            while True:
                label, _ = self.next_sample()
                obj = self._parse_label(label)
                max_count = max(max_count, obj.shape[0])
                width = obj.shape[1]
        except StopIteration:
            pass
        self.reset()
        return (max_count, width)

    def reshape(self, data_shape=None, label_shape=None):
        """ref: detection.py ImageDetIter.reshape."""
        if data_shape is not None:
            self._provide_data = [io_mod.DataDesc(
                self.provide_data[0][0],
                (self.batch_size,) + tuple(data_shape), "float32")]
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self._provide_label = [io_mod.DataDesc(
                self.provide_label[0][0],
                (self.batch_size,) + tuple(label_shape), "float32")]
            self.label_shape = tuple(label_shape)

    def next(self):
        bs = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((bs, h, w, c), np.float32)
        batch_label = np.full((bs,) + self.label_shape, -1.0, np.float32)
        i = 0
        try:
            while i < bs:
                label, s = self.next_sample()
                try:
                    data = imdecode(s)
                    obj = self._parse_label(label)
                    arr = data
                    for aug in self.auglist:
                        arr, obj = aug(arr, obj)
                except RuntimeError as e:
                    logging.debug("Invalid sample, skipping: %s", e)
                    continue
                batch_data[i] = (arr.asnumpy()
                                 if hasattr(arr, "asnumpy") else arr)
                n = min(obj.shape[0], self.label_shape[0])
                batch_label[i, :n, :obj.shape[1]] = obj[:n]
                i += 1
        except StopIteration:
            if not i:
                raise
        data = nd.array(batch_data.transpose(0, 3, 1, 2))
        return io_mod.DataBatch([data], [nd.array(batch_label)], bs - i)
