"""Image loading + augmentation (ref: python/mxnet/image/image.py, 1.4k LoC).

Host-side pipeline: decode (OpenCV, like the reference's src/io augmenters),
numpy/NDArray transforms, augmenter registry, and ``ImageIter`` — the python
twin of the C++ ImageRecordIter (src/io/iter_image_recordio_2.cc).  Device
work (normalize etc.) stays in XLA ops; this module is the CPU data plane.
"""
from __future__ import annotations

import logging
import os
import random

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as nd
from .. import recordio, io as io_mod

__all__ = ["imdecode", "imread", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "RandomOrderAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "HorizontalFlipAug", "CastAug", "CreateAugmenter",
           "Augmenter", "ImageIter"]


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes to HWC NDArray (ref: image.py imdecode → cv2)."""
    import cv2
    img = cv2.imdecode(np.frombuffer(bytes(buf), np.uint8),
                       cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("Decoding image failed")
    if flag == 0:
        img = img[:, :, None]
    elif to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img, dtype=np.uint8)


def imread(filename, flag=1, to_rgb=True):
    """ref: image.py imread."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    """ref: image.py imresize (cv2 interps 0..4)."""
    import cv2
    return nd.array(cv2.resize(src.asnumpy(), (w, h), interpolation=interp),
                    dtype=src.dtype)


def scale_down(src_size, size):
    """Scale crop size if bigger than image (ref: image.py scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (ref: image.py resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """ref: image.py fixed_crop."""
    out = NDArray(src._read()[y0:y0 + h, x0:x0 + w], ctx=src.context)
    if size is not None and (w, h) != size:
        out = imresize(out, *size, interp=interp)
    return out


def random_crop(src, size, interp=2):
    """ref: image.py random_crop."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """ref: image.py center_crop."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """ref: image.py color_normalize."""
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (ref: image.py random_size_crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = random.uniform(area[0], area[1]) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        new_ratio = np.exp(random.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


class Augmenter(object):
    """Image augmenter base (ref: image.py class Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                v = v.asnumpy()
            if isinstance(v, np.ndarray):
                v = v.tolist()
            self._kwargs[k] = v

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    """ref: image.py ResizeAug (resize shorter edge)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """ref: image.py ForceResizeAug."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    """Apply augmenters in random order (ref: image.py RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        gray = src.asnumpy() * self._coef
        gray = (3.0 * (1.0 - alpha) / gray.size) * np.sum(gray)
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        gray = (src.asnumpy() * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + nd.array(gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """ref: image.py HueJitterAug (yiq rotation)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        return nd.array(np.dot(src.asnumpy(), np.array(t, np.float32)))


class ColorJitterAug(RandomOrderAug):
    """ref: image.py ColorJitterAug."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting jitter (ref: image.py LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb.astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean if mean is None or isinstance(mean, NDArray) \
            else nd.array(np.asarray(mean, np.float32))
        self.std = std if std is None or isinstance(std, NDArray) \
            else nd.array(np.asarray(std, np.float32))

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _mat = np.array([[0.21, 0.21, 0.21],
                     [0.72, 0.72, 0.72],
                     [0.07, 0.07, 0.07]], np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            src = nd.array(np.dot(src.asnumpy(), self._mat))
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            src = NDArray(src._read()[:, ::-1], ctx=src.context)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Standard augmenter pipeline factory (ref: image.py CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.any(np.asarray(mean) > 0):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class _NativeSeqReader(object):
    """MXRecordIO-shaped facade over the C++ background-prefetch reader
    (src/io/recordio.cc MXTPUPrefetchReader*): `read()` returns framed
    payloads that were fetched ahead by the native thread; `reset()`
    reopens (the native reader is forward-only by design, like
    dmlc::ThreadedIter)."""

    def __init__(self, path, capacity=64):
        from .. import _native
        self._path = path
        self._capacity = capacity
        self._reads = 0
        self._reader = _native.NativePrefetchReader(path, capacity)

    def read(self):
        self._reads += 1
        return self._reader.read()

    def reset(self):
        if not self._reads:
            return  # fresh reader (e.g. the reset() in __init__) — keep it
        from .. import _native
        self._reader.close()
        self._reader = _native.NativePrefetchReader(self._path,
                                                    self._capacity)
        self._reads = 0

    def close(self):
        self._reader.close()


class ImageIter(io_mod.DataIter):
    """Image iterator over .rec files or .lst/image folders with augmenters
    (ref: image.py class ImageIter — python twin of ImageRecordIter).

    ``preprocess_threads`` > 1 decodes + augments the batch on a thread
    pool (cv2 releases the GIL, so decode genuinely parallelizes — the
    role of MXNET_CPU_WORKER_NTHREADS in iter_image_recordio_2.cc:663).
    Sequential .rec reads ride the native C++ prefetch reader
    (src/io/recordio.cc) when the library is built, so file IO + record
    framing overlap Python-side decode.

    ``decode='raw'`` treats each record payload as the raw uint8 HWC
    tensor of ``data_shape`` (written by tools/im2rec.py --pack-raw) and
    skips JPEG decode entirely — the pre-decoded fast path for feeding a
    TPU at rates a host JPEG decoder can't sustain; ``'auto'`` sniffs by
    payload size, ``'jpeg'`` forces cv2.
    """

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root=None, path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 dtype="float32", preprocess_threads=1, decode="auto",
                 ctx=None, **kwargs):
        super().__init__()
        self._out_ctx = ctx  # batch placement; ctx=cpu(0) keeps batches
        # host-side so the consumer owns the (single) accelerator upload —
        # essential when a prefetch thread would otherwise contend with
        # the training step for the device transport
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.seq = None
        self.imgrec = None
        self.imglist = None
        self._native_path = None
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(path_imgidx,
                                                         path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                assert not shuffle and num_parts <= 1, \
                    "path_imgidx is required when shuffle or num_parts > 1 " \
                    "is used with a .rec file (ref: image.py:1115)"
                self.imgrec = self._open_sequential(path_imgrec)
        if path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in fin:
                    line = line.strip().split("\t")
                    label = np.array(line[1:-1], dtype=np.float32)
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
                self.seq = imgkeys
        elif isinstance(imglist, list):
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = index
                index += 1
                if isinstance(img[0], (int, float)):
                    label = np.array([img[0]], np.float32)
                else:
                    label = np.array(img[0], np.float32)
                result[key] = (label, img[-1])
                imgkeys.append(key)
            self.imglist = result
            self.seq = imgkeys

        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C:(part_index + 1) * C]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.dtype = dtype
        if np.dtype(dtype) == np.uint8:
            # range-shifting augmenters (normalize/jitter/lighting) emit
            # negative / out-of-range floats that would WRAP when stored
            # in a uint8 batch; geometric augs + cast stay in 0..255 and
            # are fine (the reference's ImageRecordUInt8Iter likewise
            # forbids only normalization on the uint8 path)
            unsafe = (ColorNormalizeAug, LightingAug, ColorJitterAug,
                      HueJitterAug, BrightnessJitterAug, ContrastJitterAug,
                      SaturationJitterAug)

            def _flatten_augs(augs):
                for a in augs:
                    yield a
                    # composite augmenters (RandomOrderAug etc.) hold
                    # their children in .ts — recurse so a wrapped
                    # normalizer cannot slip past the guard
                    yield from _flatten_augs(getattr(a, "ts", []))
            bad = [a for a in _flatten_augs(self.auglist)
                   if isinstance(a, unsafe)]
            if bad:
                raise ValueError(
                    "dtype='uint8' cannot be combined with range-shifting "
                    "augmenters %r — their float output would wrap in the "
                    "uint8 batch buffer" % ([type(a).__name__ for a in bad]))
        self.preprocess_threads = max(int(preprocess_threads), 1)
        self._decode_mode = decode
        self._pool = None
        if self.preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(self.preprocess_threads,
                                            thread_name_prefix="imgdec")
        self._provide_data = [io_mod.DataDesc(data_name,
                                              (batch_size,) + data_shape, dtype)]
        self._provide_label = [io_mod.DataDesc(label_name,
                                               (batch_size, label_width)
                                               if label_width > 1
                                               else (batch_size,),
                                               "float32")]
        self.reset()

    def _open_sequential(self, path):
        """Sequential .rec reader: native background-thread prefetch reader
        when libmxtpu_io is built (src/io/recordio.cc PrefetchReader),
        pure-Python MXRecordIO otherwise."""
        from .. import _native
        if _native.available():
            self._native_path = path
            return _NativeSeqReader(path)
        return recordio.MXRecordIO(path, "r")

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def reset(self):
        if self.shuffle and self.seq is not None:
            random.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def close(self):
        """Shut the decode pool (and the native prefetch reader) down —
        without it the pool's threads outlive the iterator (GL204) and
        read as phantom in-flight work in crash dumps."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self.imgrec is not None and hasattr(self.imgrec, "close"):
            self.imgrec.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass                # interpreter teardown

    def __exit__(self, et, ev, tb):
        self.close()
        return False

    def __enter__(self):
        return self

    def _decode_np(self, s):
        """Payload → HWC uint8 numpy image; raw passthrough when configured.
        Stays in numpy — NDArray wrapping happens only if augmenters run."""
        c, h, w = self.data_shape
        head = bytes(s[:4])
        looks_encoded = (head.startswith(b"\xff\xd8\xff")      # JPEG SOI
                         or head.startswith(b"\x89PNG")        # PNG
                         or head.startswith(b"GIF8")           # GIF
                         or head.startswith(b"BM"))            # BMP (2-byte
        # magic: a raw tensor starting with pixels 66,77 routes to
        # cv2.imdecode and fails LOUDLY — pass decode='raw' for raw recs)
        if self._decode_mode == "raw" or (
                self._decode_mode == "auto" and len(s) == c * h * w
                and not looks_encoded):
            # auto: exact raw-tensor length AND no >=3-byte image magic —
            # a JPEG that compresses to exactly c*h*w bytes must still
            # decode, while raw pixels almost never spell a full signature
            return np.frombuffer(s, np.uint8).reshape(h, w, c)
        import cv2
        img = cv2.imdecode(np.frombuffer(bytes(s), np.uint8),
                           cv2.IMREAD_COLOR)
        if img is None:
            raise MXNetError("Decoding image failed")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)

    def next_sample(self):
        """Return (label, decoded image) (ref: image.py next_sample)."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as f:
                img = f.read()
            return label, img
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _process_one(self, s):
        """decode + augment one payload, pinned to the CPU context so the
        host data plane never round-trips through the accelerator.  With
        an empty aug_list the sample never leaves numpy."""
        img = self._decode_np(s)
        if not self.auglist:
            return img
        from ..context import cpu
        with cpu(0):
            data = nd.array(img, dtype=np.uint8)
            for aug in self.auglist:
                data = aug(data)
            return data.asnumpy()

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        # uint8 dtype keeps the whole host path cast-free (the reference's
        # ImageRecordUInt8Iter); the device does the f32/bf16 conversion
        buf_dtype = (np.uint8 if np.dtype(self.dtype) == np.uint8
                     else np.float32)
        batch_data = np.zeros((batch_size, h, w, c), buf_dtype)
        batch_label = np.zeros((batch_size, self.label_width), np.float32)
        # stage 1: pull raw samples sequentially (record framing is cheap
        # and ordered); stage 2: decode+augment, on the pool when asked
        raws = []
        try:
            while len(raws) < batch_size:
                raws.append(self.next_sample())
        except StopIteration:
            if not raws:
                raise
        i = len(raws)
        if self._pool is not None:
            images = list(self._pool.map(self._process_one,
                                         [s for _, s in raws]))
        else:
            images = [self._process_one(s) for _, s in raws]
        for j, ((label, _), img) in enumerate(zip(raws, images)):
            batch_data[j] = img
            batch_label[j] = label
        # materialize NCHW contiguously on the host: a strided view handed
        # to device_put uploads element-wise (measured 26x slower through
        # the device tunnel than a contiguous buffer)
        data = nd.array(np.ascontiguousarray(batch_data.transpose(0, 3, 1, 2)),
                        dtype=self.dtype, ctx=self._out_ctx)
        # labels stay float32 regardless of the image dtype: a uint8 cast
        # would wrap class ids >= 256 (reference ImageRecordUInt8Iter
        # likewise types only the data blob)
        label = nd.array(batch_label.reshape(-1) if self.label_width == 1
                         else batch_label, dtype="float32",
                         ctx=self._out_ctx)
        return io_mod.DataBatch([data], [label], batch_size - i)
