"""Image API (ref: python/mxnet/image/__init__.py)."""
from .image import *
from . import image
