"""Image API (ref: python/mxnet/image/__init__.py)."""
from .image import *
from .detection import *
from . import detection
from . import image
