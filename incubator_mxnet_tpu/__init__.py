"""incubator_mxnet_tpu — a TPU-native deep-learning framework with the
capabilities of Apache MXNet 1.2 (reference: jinhuang415/incubator-mxnet).

Not a port: JAX/XLA is the compile+execute substrate, Pallas the custom-kernel
path, pjit/shard_map + XLA collectives the distributed fabric.  See SURVEY.md
at the repo root for the blueprint and per-module docstrings for the
reference-parity map (file:line citations into /root/reference).

Import convention mirrors the reference:

    import incubator_mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
"""
__version__ = "0.1.0"

from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, current_context, num_devices

from . import ops
from . import ndarray
from . import ndarray as nd  # canonical alias, as in mxnet
from .ndarray import NDArray

from . import autograd
from . import engine
from . import random
from . import random_state

from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym  # canonical alias, as in mxnet
from .symbol import Symbol

from . import lr_scheduler
from . import optimizer
from . import optimizer as opt  # alias, as in mxnet
from . import initializer
from . import initializer as init  # alias, as in mxnet
from .initializer import Xavier

from . import name
from . import kvstore
from . import kvstore as kv  # alias, as in mxnet
from . import io
from . import recordio
from . import image
from . import metric
from . import callback
from . import monitor
from . import module
from . import module as mod  # alias, as in mxnet
from . import model
from . import gluon
from . import parallel
from . import contrib
from . import operator
from . import rnn
from . import executor_manager
from . import rtc
from . import profiler
from . import telemetry
from . import config
from . import visualization
from . import visualization as viz

# env-var driven startup behavior (SURVEY §5.6 config layer)
if config.get_bool("PROFILER_AUTOSTART"):
    import atexit as _atexit
    profiler.set_config(continuous_dump=True)
    profiler.set_state("run")
    _atexit.register(lambda: profiler.set_state("stop"))
if config.get_int("SEED") is not None:
    random.seed(config.get_int("SEED"))
