"""Optimizer registry and weight-update machinery.

TPU-native rebirth of python/mxnet/optimizer.py (1,519 LoC): the same
registry of optimizers, the same ``update(index, weight, grad, state)``
contract, dispatching to the *fused update operators* in
``ops/optimizer_ops.py`` (reference: src/operator/optimizer_op.cc) so the
whole update compiles to a handful of XLA elementwise kernels on the TPU's
VPU — the reason the reference fused them by hand.

The ``Updater`` wrapper (ref: optimizer.py get_updater) carries per-index
state dicts and is picklable, which is what lets a KVStore server run the
optimizer remotely (ref: kvstore_dist_server.h:145 server-side updater).
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from .base import MXNetError
from .engine import BoundedCache, unflatten
from .ndarray import NDArray, invoke
from .ndarray import ndarray as _nd_mod
from .ops.registry import get_op
import jax
import jax.numpy as jnp

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta", "RMSProp",
           "Ftrl", "FTML", "Signum", "SGLD", "DCASGD", "LBSGD", "Test",
           "create", "register", "get_updater", "Updater",
           "fused_bucket_kind", "fused_bucket_update", "fused_lr_wd",
           "fused_state_arity"]


class Optimizer(object):
    """Base optimizer (ref: python/mxnet/optimizer.py class Optimizer).

    Tracks per-parameter learning-rate/wd multipliers, update counts and
    the rescale/clip policy shared by every optimizer.
    """

    opt_registry = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision

        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = dict(param_idx2name)
        self.sym_info = None
        self.param_dict = param_dict if param_dict else {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- registry ----------------------------------------------------------
    @staticmethod
    def register(klass):
        """ref: optimizer.py Optimizer.register."""
        name = klass.__name__.lower()
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        """ref: optimizer.py create_optimizer."""
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    # -- state -------------------------------------------------------------
    def create_state(self, index, weight):
        """Return optimizer state for one parameter (momentum etc.)."""
        return None

    def create_state_multi_precision(self, index, weight):
        """ref: optimizer.py — fp16/bf16 weights get an f32 master copy."""
        if self.multi_precision and weight.dtype in (np.dtype("float16"),
                                                     np.dtype("bfloat16")):
            weight_master_copy = weight.astype("float32")
            return (self.create_state(index, weight_master_copy), weight_master_copy)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype in (np.dtype("float16"),
                                                     np.dtype("bfloat16")):
            inner_state, weight32 = state
            g32 = grad.astype("float32")
            self.update(index, weight32, g32, inner_state)
            weight._write(weight32._read().astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    # -- lr / wd policy ----------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been defined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        """ref: optimizer.py set_lr_mult (incl. __lr_mult__ symbol attrs)."""
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """ref: optimizer.py set_wd_mult — biases/gammas default to wd 0."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            # parity with reference heuristic: no decay on bias/bn params
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def __getstate__(self):
        d = dict(self.__dict__)
        d["param_dict"] = {}  # Parameters aren't picklable / needed serverside
        return d


register = Optimizer.register
create = Optimizer.create_optimizer


def _common_kwargs(opt, index):
    kw = {"lr": opt._get_lr(index), "wd": opt._get_wd(index),
          "rescale_grad": opt.rescale_grad}
    if opt.clip_gradient is not None:
        kw["clip_gradient"] = opt.clip_gradient
    return kw


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision
    (ref: optimizer.py class SGD → sgd_update/sgd_mom_update/mp_* ops)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (np.dtype("float16"),
                                                     np.dtype("bfloat16")):
            weight32 = weight.astype("float32")
            return (self.create_state(index, weight32), weight32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = _common_kwargs(self, index)
        kw["lazy_update"] = self.lazy_update
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray) and self.lazy_update:
            # lazy update: touch only occupied rows (ref: optimizer_op.cc
            # SGDUpdateRspRspImpl — the row-sparse kernel)
            self._sparse_sgd(weight, grad, state, kw)
            return
        if state is not None:
            kw["momentum"] = self.momentum
            invoke(get_op("sgd_mom_update"), [weight, grad, state], kw, out=weight)
        else:
            invoke(get_op("sgd_update"), [weight, grad], kw, out=weight)

    def _sparse_sgd(self, weight, grad, state, kw):
        # registered ops (not inline jnp) so engine.bulk can defer the
        # lazy update into a training segment — the reference bulks
        # optimizer updates too (threaded_engine.h train segments)
        ukw = {"lr": kw["lr"], "wd": kw["wd"],
               "rescale_grad": kw["rescale_grad"],
               "clip_gradient": kw.get("clip_gradient", -1.0)}
        if state is not None:
            ukw["momentum"] = self.momentum
            invoke(get_op("_sparse_sgd_mom_update"),
                   [weight, grad.data, grad.indices, state], ukw, out=weight)
        else:
            invoke(get_op("_sparse_sgd_update"),
                   [weight, grad.data, grad.indices], ukw, out=weight)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype in (np.dtype("float16"),
                                                           np.dtype("bfloat16"))
        if not use_mp:
            return self.update(index, weight, grad, state)
        self._update_count(index)
        kw = _common_kwargs(self, index)
        mom, weight32 = state
        if mom is not None:
            kw["momentum"] = self.momentum
            invoke(get_op("mp_sgd_mom_update"), [weight, grad, mom, weight32],
                   kw, out=weight)
        else:
            invoke(get_op("mp_sgd_update"), [weight, grad, weight32], kw, out=weight)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref: optimizer.py class NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = _common_kwargs(self, index)
        if state is not None:
            kw["momentum"] = self.momentum
            invoke(get_op("nag_mom_update"), [weight, grad, state], kw, out=weight)
        else:
            invoke(get_op("sgd_update"), [weight, grad], kw, out=weight)


@register
class Adam(Optimizer):
    """Adam (ref: optimizer.py class Adam → adam_update op; bias correction
    folded into lr, as in the reference)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        return (_nd_mod.invoke(z, [weight], {}), _nd_mod.invoke(z, [weight], {}))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        kw = {"lr": lr, "wd": self._get_wd(index), "rescale_grad": self.rescale_grad,
              "beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
              "lazy_update": self.lazy_update}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        mean, var = state
        invoke(get_op("adam_update"), [weight, grad, mean, var], kw, out=weight)


@register
class AdaGrad(Optimizer):
    """ref: optimizer.py class AdaGrad (python updater in the reference —
    here it's a jitted op-free update over NDArray math)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._read()
        hist = state._read() + jnp.square(g)
        state._write(hist)
        weight._write(weight._read() - lr * g / (jnp.sqrt(hist) + self.float_stable_eps))


@register
class AdaDelta(Optimizer):
    """ref: optimizer.py class AdaDelta."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        return (_nd_mod.invoke(z, [weight], {}), _nd_mod.invoke(z, [weight], {}))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._read()
        acc_g, acc_delta = state
        ag = self.rho * acc_g._read() + (1 - self.rho) * jnp.square(g)
        delta = jnp.sqrt(acc_delta._read() + self.epsilon) / jnp.sqrt(ag + self.epsilon) * g
        ad = self.rho * acc_delta._read() + (1 - self.rho) * jnp.square(delta)
        acc_g._write(ag)
        acc_delta._write(ad)
        weight._write(weight._read() - delta)


@register
class RMSProp(Optimizer):
    """ref: optimizer.py class RMSProp — non-centered (rmsprop_update) and
    centered/Alex variant (rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        if self.centered:
            return (_nd_mod.invoke(z, [weight], {}), _nd_mod.invoke(z, [weight], {}),
                    _nd_mod.invoke(z, [weight], {}))
        return _nd_mod.invoke(z, [weight], {})

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = _common_kwargs(self, index)
        kw["gamma1"] = self.gamma1
        kw["epsilon"] = self.epsilon
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            kw["gamma2"] = self.gamma2
            invoke(get_op("rmspropalex_update"), [weight, grad, n, g, delta],
                   kw, out=weight)
        else:
            invoke(get_op("rmsprop_update"), [weight, grad, state], kw, out=weight)


@register
class Ftrl(Optimizer):
    """ref: optimizer.py class Ftrl → ftrl_update op."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        return (_nd_mod.invoke(z, [weight], {}), _nd_mod.invoke(z, [weight], {}))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = _common_kwargs(self, index)
        kw["lamda1"] = self.lamda1
        kw["beta"] = self.beta
        z, n = state
        invoke(get_op("ftrl_update"), [weight, grad, z, n], kw, out=weight)


@register
class FTML(Optimizer):
    """ref: optimizer.py class FTML → ftml_update op."""

    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        return (_nd_mod.invoke(z, [weight], {}), _nd_mod.invoke(z, [weight], {}),
                _nd_mod.invoke(z, [weight], {}))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = _common_kwargs(self, index)
        kw.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, t=t)
        d, v, z = state
        invoke(get_op("ftml_update"), [weight, grad, d, v, z], kw, out=weight)


@register
class Signum(Optimizer):
    """ref: optimizer.py class Signum → signsgd_update/signum_update ops."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = _common_kwargs(self, index)
        if state is not None:
            kw["momentum"] = self.momentum
            kw["wd_lh"] = self.wd_lh
            invoke(get_op("signum_update"), [weight, grad, state], kw, out=weight)
        else:
            invoke(get_op("signsgd_update"), [weight, grad], kw, out=weight)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref: optimizer.py class SGLD)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        g = g + wd * weight._read()
        from . import random_state
        import jax
        noise = jax.random.normal(random_state.next_key(), weight.shape,
                                  weight._read().dtype) * math.sqrt(lr)
        weight._write(weight._read() - lr / 2 * g + noise)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref: optimizer.py class DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        z = get_op("zeros_like")
        mom = None if self.momentum == 0.0 else _nd_mod.invoke(z, [weight], {})
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        g = grad._read() * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mon, previous_weight = state
        w = weight._read()
        comp = g + wd * w + self.lamda * g * g * (w - previous_weight._read())
        if mon is not None:
            m = self.momentum * mon._read() - lr * comp
            mon._write(m)
        else:
            m = -lr * comp
        previous_weight._write(w)
        weight._write(w + m)


@register
class LBSGD(Optimizer):
    """Large-batch SGD with LARS-style layer-wise adaptive rate
    (ref: optimizer.py class LBSGD, simplified warmup strategies)."""

    def __init__(self, momentum=0.0, multi_precision=False, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32, begin_epoch=0,
                 num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = batch_scale
        self.updates_per_epoch = updates_per_epoch
        self.init_updates = begin_epoch * updates_per_epoch
        self.num_epochs = num_epochs
        self.lbmult = 1.0

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def _get_lbmult(self, nup):
        nwup = self.warmup_epochs * self.updates_per_epoch
        if self.warmup_strategy == "linear" and nwup > 0 and nup < nwup:
            return 1.0 + (self.batch_scale - 1.0) * nup / nwup
        return float(self.batch_scale)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        self.lbmult = self._get_lbmult(self.num_update + self.init_updates)
        lr = self._get_lr(index) * self.lbmult
        kw = {"lr": lr, "wd": self._get_wd(index), "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        if state is not None:
            kw["momentum"] = self.momentum
            invoke(get_op("sgd_mom_update"), [weight, grad, state], kw, out=weight)
        else:
            invoke(get_op("sgd_update"), [weight, grad], kw, out=weight)


@register
class Test(Optimizer):
    """ref: optimizer.py class Test — w += rescale_grad * grad (for testing)."""

    def create_state(self, index, weight):
        return _nd_mod.invoke(get_op("zeros_like"), [weight], {})

    def update(self, index, weight, grad, state):
        weight._write(weight._read() + self.rescale_grad * grad._read())
        state._write(weight._read())


# alias casing parity: mx.optimizer.create('sgd' | 'SGD' | ...)
Optimizer.opt_registry["sgd"] = SGD
Optimizer.opt_registry["adam"] = Adam


class Updater(object):
    """Per-index stateful updater closure (ref: optimizer.py class Updater /
    get_updater) — this object is what KVStore servers pickle and run."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def ensure_state(self, index, weight):
        """Create (or context-sync) the state for ``index`` exactly as
        ``__call__`` would.  The fused bucket-update path (graftfuse)
        shares this per-index store, so save_states/load_states and
        switching between fused and per-param execution stay seamless."""
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index], weight.context)
            self.states_synced[index] = True
        return self.states[index]

    def __call__(self, index, grad, weight):
        state = self.ensure_state(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, state)

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, np.ndarray):
            # states loaded via set_states arrive as numpy — rehydrate so
            # the fused update ops can read them
            return _nd_mod.array(state, ctx=context)
        if isinstance(state, (tuple, list)):
            return type(state)(self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        """ref: optimizer.py Updater.set_states (pickle format).

        Loaded leaves stay numpy until first use — sync_state_context
        rehydrates them as NDArrays on the weight's context lazily.
        """
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            states, self.optimizer = states
        self.states = dict(states)
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(i) for i in s)
            if hasattr(s, "shape") and hasattr(s, "dtype"):
                # device arrays parked directly in the store (graftzero's
                # error-feedback residuals) — persist as plain numpy so
                # snapshots never pickle framework device buffers
                return np.asarray(s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer else states)

    def states_nbytes(self):
        """Optimizer-state bytes this updater holds — a metadata walk
        (shape x dtype, never forces a device flush) over the int-keyed
        per-param states only; string-keyed side entries (graftzero's
        error-feedback residuals) are wire state, not optimizer state,
        and are counted by their own telemetry.  This is what the
        ``graft_trainer_state_shard_bytes`` gauge reports: under ZeRO-1
        sharding each rank's updater holds ~1/N of the unsharded total."""
        def leaf_nbytes(s):
            if isinstance(s, NDArray):
                arr = s._read()
                return int(np.dtype(arr.dtype).itemsize) * int(np.prod(arr.shape, dtype=np.int64))
            if isinstance(s, np.ndarray):
                return int(s.nbytes)
            if isinstance(s, (tuple, list)):
                return sum(leaf_nbytes(i) for i in s)
            return 0
        return sum(leaf_nbytes(v) for k, v in self.states.items()
                   if isinstance(k, int))


def get_updater(optimizer):
    """ref: optimizer.py get_updater."""
    return Updater(optimizer)


# ---------------------------------------------------------------------------
# graftfuse: multi-tensor fused bucket updates
# ---------------------------------------------------------------------------
# The per-param path dispatches one optimizer kernel per (param, context) —
# N tiny XLA programs per step, each crossing the host via invoke().  The
# fused path compiles ONE jitted program per (optimizer-class, bucket
# signature) that updates every parameter of a dtype-homogeneous bucket in
# a single dispatch: gradients arrive either as the bucket's flat reduced
# buffer (sliced/unflattened inside the program — free under XLA fusion)
# or as the per-param arrays, the per-param update formulas are the exact
# registered op fcomputes (sgd_update / sgd_mom_update / mp_* / adam_update),
# and the outputs rebind each weight/state NDArray without any device work.
# lr / wd / rescale_grad are baked into the program as constants — the
# same layout the per-param jits use, which is what makes the fused
# programs compile (and round) identically to the standalone ones; the
# cache key includes them, mirroring the per-param Operator.bind cache
# that also keys on these scalars.  Bit-exactness with the per-param path
# holds because every element goes through the same elementwise op chain
# with the same constant structure (tests/test_trainer_fused.py pins this
# down byte-for-byte).  Cached like the engine's _replay_cache, with the
# same GRAFT_REPLAY_CACHE_SIZE bound.

_FUSED_STEP_CACHE = BoundedCache()

_HALF_DTYPES = (np.dtype("float16"), np.dtype("bfloat16"))


def fused_bucket_kind(optimizer, dtype):
    """Fused-program tag for parameters of ``dtype`` under ``optimizer``,
    or None when that combination must take the per-param path.  Exact
    type checks (not isinstance): a subclass may override update() and
    silently diverge from the fused formula."""
    dtype = np.dtype(dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        return None
    if type(optimizer) is SGD:
        if optimizer.multi_precision and dtype in _HALF_DTYPES:
            return "mp_sgd"
        return "sgd"
    if type(optimizer) is Adam:
        if optimizer.multi_precision and dtype in _HALF_DTYPES:
            return None     # base-class mp wrapper: keep per-param
        return "adam"
    return None


def fused_lr_wd(optimizer, index, kind):
    """One per-(param, context) bookkeeping tick in the exact per-param
    sequence: bump the update count, then resolve lr (with Adam's bias
    correction folded in, as Adam.update does) and wd."""
    optimizer._update_count(index)
    lr = optimizer._get_lr(index)
    if kind == "adam":
        t = optimizer._index_update_count[index]
        lr *= math.sqrt(1.0 - optimizer.beta2 ** t) \
            / (1.0 - optimizer.beta1 ** t)
    return lr, optimizer._get_wd(index)


def _fused_state_arrays(kind, state):
    """The NDArray leaves of one per-index state, in program order."""
    if kind == "sgd":
        return () if state is None else (state,)
    if kind == "mp_sgd":
        mom, weight32 = state
        return (weight32,) if mom is None else (mom, weight32)
    if kind == "adam":
        mean, var = state
        return (mean, var)
    raise ValueError("unknown fused kind %r" % kind)


_NO_STATE = object()


def fused_state_arity(optimizer, kind, state=_NO_STATE):
    """State-leaf count a param contributes to a fused program — from its
    EXISTING per-index state when one exists (the per-param formulas key
    off the state object, not current config: a momentum flipped mid-run
    only affects states created afterwards), else from the optimizer's
    current config.  The Trainer plan buckets by (dtype, arity) so a
    fused program never mixes formula variants."""
    if state is not _NO_STATE:
        return len(_fused_state_arrays(kind, state))
    if kind == "sgd":
        return 1 if optimizer.momentum else 0
    if kind == "mp_sgd":
        return 2 if optimizer.momentum else 1
    return 2    # adam: (mean, var)


def _fused_config(optimizer, kind):
    """Static (hashable) config baked into the fused program — part of
    the cache key; everything per-step stays a traced operand."""
    clip = optimizer.clip_gradient
    clip = -1.0 if clip is None else float(clip)
    if kind in ("sgd", "mp_sgd"):
        return (float(optimizer.momentum), clip)
    if kind == "adam":
        return (float(optimizer.beta1), float(optimizer.beta2),
                float(optimizer.epsilon), clip)
    raise ValueError("unknown fused kind %r" % kind)


def fused_formula_applier(kind, cfg, has_state, scope=None):
    """The per-bucket multi-tensor update as a PURE function —
    ``apply(weights, gs, states, lrs, wds, rescale) -> (new_w, new_s)``
    — composable into a LARGER trace (the graftstep whole-step program
    fuses it after ``jax.vjp``'s backward, ``gluon/step_compile.py``).

    ``scope`` (graftxray): an optional ``jax.named_scope`` name wrapped
    around the formula math so the ops carry it in their HLO op_name
    metadata (telemetry/xray.py attribution).  Default None emits NO
    scope — the eager graftfuse constant layout must stay bit-identical
    to the per-param path, so only the compiled step passes one.

    ``lrs``/``wds``/``rescale`` may be python floats (the constant
    layout :func:`_build_fused_program` bakes — bit-identical to the
    per-param path) or traced scalar operands (the compiled whole-step
    path, where ``set_learning_rate`` must NOT retrace; operands can
    shift LLVM's fma-contraction choices by ~1 ULP vs the constant
    layout — measured on bf16 mp_sgd — which is the documented
    EH104-style tolerance the graftstep parity tests assert under)."""
    if kind in ("sgd", "mp_sgd"):
        momentum, clip = cfg
    else:
        beta1, beta2, epsilon, clip = cfg
    sgd_fc = get_op("sgd_update").fcompute
    sgd_mom_fc = get_op("sgd_mom_update").fcompute
    mp_sgd_fc = get_op("mp_sgd_update").fcompute
    mp_sgd_mom_fc = get_op("mp_sgd_mom_update").fcompute
    adam_fc = get_op("adam_update").fcompute

    # graftlint: disable=GL305 -- cfg scalars (momentum/beta/eps/clip) are deliberately baked: the fused program cache AND the graftstep guard key both key on them
    def apply(weights, gs, states, lrs, wds, rescale):
        new_w, new_s = [], []
        for k, w in enumerate(weights):
            g = gs[k]
            lr, wd, st = lrs[k], wds[k], states[k]
            if kind == "sgd":
                if has_state:
                    w2, m2 = sgd_mom_fc(w, g, st[0], lr=lr,
                                        momentum=momentum, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip)
                    new_w.append(w2)
                    new_s.append((m2,))
                else:
                    new_w.append(sgd_fc(w, g, lr=lr, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip))
                    new_s.append(())
            elif kind == "mp_sgd":
                if has_state:
                    w2, m2, w32 = mp_sgd_mom_fc(w, g, st[0], st[1], lr=lr,
                                                momentum=momentum, wd=wd,
                                                rescale_grad=rescale,
                                                clip_gradient=clip)
                    new_w.append(w2)
                    new_s.append((m2, w32))
                else:
                    w2, w32 = mp_sgd_fc(w, g, st[0], lr=lr, wd=wd,
                                        rescale_grad=rescale,
                                        clip_gradient=clip)
                    new_w.append(w2)
                    new_s.append((w32,))
            else:
                w2, m2, v2 = adam_fc(w, g, st[0], st[1], lr=lr,
                                     beta1=beta1, beta2=beta2,
                                     epsilon=epsilon, wd=wd,
                                     rescale_grad=rescale,
                                     clip_gradient=clip)
                new_w.append(w2)
                new_s.append((m2, v2))
        return tuple(new_w), tuple(new_s)

    if scope is None:
        return apply

    def scoped_apply(weights, gs, states, lrs, wds, rescale):
        with jax.named_scope(scope):
            return apply(weights, gs, states, lrs, wds, rescale)

    return scoped_apply


def _build_fused_program(kind, cfg, shapes, flat_mode, has_state,
                         lrs, wds, rescale):
    """One unflatten→update→reflatten program over a whole bucket.

    lr/wd/rescale are baked in as python-float CONSTANTS, exactly as the
    per-param path bakes them into each op's jitted partial — traced
    scalar operands occasionally shift LLVM's fma-contraction choices by
    1 ULP (measured on bf16 mp_sgd), and constants are the only layout
    that compiles each param's formula identically to its standalone
    program.  The per-param ``Operator.bind`` cache keys on the same
    scalars, so a changing lr schedule costs the fused path exactly the
    retraces it already cost the per-param path.  The formulas
    themselves come from :func:`fused_formula_applier` — one source,
    shared with the graftstep whole-step program (which passes the same
    scalars as traced operands instead)."""
    apply = fused_formula_applier(kind, cfg, has_state)

    # graftlint: disable=GL305 -- lr/wd/rescale baked by design here: constants are the only layout bit-identical to the per-param path, and the program cache keys on them (see docstring)
    def step(weights, grads, states):
        gs = unflatten(grads, shapes) if flat_mode else grads
        return apply(weights, gs, states, lrs, wds, rescale)

    return jax.jit(step)


def fused_bucket_update(optimizer, updater, indices, weights, grads,
                        lrs, wds, flat_grad=None):
    """Apply one fused multi-tensor optimizer step to a bucket on one
    context: ``indices``/``weights`` are the bucket's params (index
    order), ``grads`` their per-param gradient NDArrays (ignored when
    ``flat_grad`` — the bucket's reduced flat buffer — is given), and
    ``lrs``/``wds`` the per-param scalars the caller resolved via
    :func:`fused_lr_wd`.  States come from (and go back to) ``updater``'s
    per-index store.  Everything stays on device: one jit dispatch, then
    pure buffer rebinds."""
    from .telemetry import metrics as _tmetrics
    kind = fused_bucket_kind(optimizer, weights[0].dtype)
    assert kind is not None, "caller must pre-check fused_bucket_kind"
    state_arrays = [
        _fused_state_arrays(kind, updater.ensure_state(i, w))
        for i, w in zip(indices, weights)]
    arity = len(state_arrays[0])
    # the Trainer plan buckets by (dtype, state arity); a mixed bucket
    # here means the plan went stale relative to the state store
    assert all(len(s) == arity for s in state_arrays), \
        "fused bucket with heterogeneous state arity — plan is stale"
    # "has_state" selects the momentum variant of the sgd/mp_sgd program
    # (mp always carries the f32 master copy, so momentum means 2 leaves)
    has_state = arity >= (2 if kind == "mp_sgd" else 1)
    cfg = _fused_config(optimizer, kind)
    shapes = tuple(tuple(w.shape) for w in weights)
    dtype = np.dtype(weights[0].dtype)
    flat_mode = flat_grad is not None
    lrs = tuple(float(v) for v in lrs)
    wds = tuple(float(v) for v in wds)
    rescale = float(optimizer.rescale_grad)
    key = (kind, cfg, shapes, str(dtype), flat_mode, has_state,
           lrs, wds, rescale)
    fn = _FUSED_STEP_CACHE.get(key)
    if fn is None:
        fn = _build_fused_program(kind, cfg, shapes, flat_mode, has_state,
                                  lrs, wds, rescale)
        _FUSED_STEP_CACHE[key] = fn
    wvals = tuple(w._read() for w in weights)
    gvals = flat_grad._read() if flat_mode \
        else tuple(g._read() for g in grads)
    svals = tuple(tuple(a._read() for a in arrs) for arrs in state_arrays)
    outs_w, outs_s = fn(wvals, gvals, svals)
    for k, w in enumerate(weights):
        w._write(outs_w[k])
        for arr, val in zip(state_arrays[k], outs_s[k]):
            arr._write(val)
    _tmetrics.trainer_fused_update(len(weights))
