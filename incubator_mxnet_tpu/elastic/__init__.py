"""graftelastic — live membership change for the dist training fleet.

graftarmor (PR 15) made a dead rank a *detectable, typed* event; this
package makes it a *survivable* one.  Three pieces (ISSUE 20 /
docs/robustness.md "Elasticity"):

* :mod:`.membership` — epoch-fenced membership: a deterministic
  :class:`MembershipView` per epoch, a :class:`Membership` state
  machine that applies queued changes behind the Trainer's step
  barrier (quiesce the duplex wire, re-partition PS key ranges and
  ZeRO ``shard_owners``, rebuild bucket plans, re-base the lockstep
  auditor's fold stream), and pure re-partition helpers whose outputs
  depend only on ``(keys, world_size)`` — every survivor computes the
  same maps with no coordinator.
* :mod:`.rejoin` — checkpoint-streamed rejoin: a replacement rank
  pulls the newest VALIDATED armor snapshot (params + optimizer-shard
  blobs + ``__quant_ef__`` residuals — everything the checkpointer
  already captures) over the PS wire in buckets, validates the
  manifest hash, restores, and joins at the next epoch fence.
* :mod:`.harness` — a single-process simulated-N-rank cluster
  (virtual ranks, a shard-ordered deterministic reduce wire, real
  ``Membership`` objects per rank) so kill → re-partition → rejoin →
  byte-parity runs as REAL coverage in one process, no multi-host
  cluster required.

Master switch ``GRAFT_ELASTIC`` (default off — bit-identical inert:
the only enabled-path cost on a quiet step is one memoized env read
plus an empty-queue check, gated < 2% by ``bench_eager.py --smoke``).
Like every collective-shape switch (``GRAFT_BLACKBOX``,
``GRAFT_LOCKSTEP_CHECK``) set it IDENTICALLY on every rank: the dist
heartbeat vector grows a membership-epoch block when it is on.

``python -m incubator_mxnet_tpu.elastic --selftest`` proves the
kill → re-partition → rejoin → byte-parity loop (lint tier 14).
"""
from __future__ import annotations

import os

from .membership import (MembershipView, Membership, key_owner,
                         repartition_plan, repartition_shard_states,
                         merge_shard_states)
from .rejoin import (InProcessByteStore, stream_snapshot, fetch_snapshot,
                     rejoin_trainer, rejoin_timeout)

__all__ = [
    "enabled", "set_enabled",
    "MembershipView", "Membership", "key_owner", "repartition_plan",
    "repartition_shard_states", "merge_shard_states",
    "InProcessByteStore", "stream_snapshot", "fetch_snapshot",
    "rejoin_trainer", "rejoin_timeout",
]

_enabled_override = None
_cache = [None, False]          # (raw env string, verdict) — hot-path memo


def set_enabled(flag):
    """Force elastic on/off (None = defer to GRAFT_ELASTIC)."""
    global _enabled_override
    _enabled_override = flag


def enabled():
    """GRAFT_ELASTIC (default off), memoized on the raw string — this
    sits on Trainer.step's hot path, so the steady-state cost is one
    dict lookup and a pointer compare."""
    if _enabled_override is not None:
        return bool(_enabled_override)
    raw = os.environ.get("GRAFT_ELASTIC")
    if raw != _cache[0]:
        _cache[0] = raw
        _cache[1] = (raw or "").strip().lower() in ("1", "on", "true",
                                                    "yes")
    return _cache[1]
