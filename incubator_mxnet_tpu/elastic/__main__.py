"""graftelastic CLI.

    python -m incubator_mxnet_tpu.elastic --selftest
        Lint smoke tier for live membership change:

        * membership algebra — view advance is pure and epoch-monotonic,
          the re-partition key plan is deterministic and minimal, and
          ``key_owner`` agrees with the PS wire's placement hash;
        * kill + rejoin byte parity — a simulated 3-rank cluster loses a
          rank mid-training and streams it back in via an armor
          snapshot; the faulted run's loss trajectory and final params
          are BYTE-identical to the unfaulted baseline and the virtual
          lockstep digests agree across >= 2 membership epochs;
        * PS-wire snapshot stream — against a REAL ParameterServer +
          PSClient pair: a chunked snapshot round-trips bit-exactly and
          a mangled stream raises typed ``CheckpointCorruptError``;
        * chaos determinism — seeded ``membership.join`` /
          ``membership.repartition`` faults replay identically; a
          dropped re-partition leaves the rank on the OLD epoch (the
          divergence the lockstep auditor names); a stream that never
          appears raises ``CollectiveTimeoutError`` in budget; a stuck
          quiesce raises ``QuiesceTimeoutError`` naming the pending
          count;
        * shard re-partition across world sizes — a ZeRO snapshot
          restores onto a DIFFERENT shard count when GRAFT_ELASTIC=1
          (deterministic merge) and refuses with a typed
          ``ShardOwnershipError`` naming the epoch when off — both
          grow and shrink directions;
        * inertness — GRAFT_ELASTIC=0 leaves ``enabled()`` false and the
          step fence untaken.

        Exit 1 on any regression.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

_ENV_KEYS = ("GRAFT_ELASTIC", "GRAFT_FAULTS", "GRAFT_REJOIN_TIMEOUT",
             "GRAFT_QUIESCE_TIMEOUT", "GRAFT_BUCKET_BYTES",
             "GRAFT_SHARD_OPTIMIZER")


def _membership_algebra(check):
    import zlib
    from .membership import (MembershipView, key_owner, repartition_plan,
                             merge_shard_states, repartition_shard_states)

    v0 = MembershipView(0, range(4))
    check(v0.world_size == 4 and v0.ranks == (0, 1, 2, 3),
          "launch view must hold the sorted launch ranks")
    v1 = v0.advance(departed=[2])
    check(v1.epoch == 1 and v1.ranks == (0, 1, 3)
          and v1.departed == (2,),
          "advance(departed) must drop the rank and bump the epoch")
    v2 = v1.advance(joined=[2])
    check(v2.epoch == 2 and v2.ranks == (0, 1, 2, 3),
          "advance(joined) must restore the rank at the NEXT epoch")
    check(v0.advance(departed=[2]) == v1,
          "advance must be pure: equal inputs, equal views")
    try:
        MembershipView(0, [7]).advance(departed=[7])
        check(False, "a change leaving zero ranks must raise")
    except ValueError:
        pass

    keys = ["w%d" % i for i in range(32)]
    check(key_owner("w3", 4) == zlib.crc32(b"w3") % 4,
          "key_owner must be the PS wire's crc32 placement hash")
    plan_a = repartition_plan(keys, 4, 3)
    plan_b = repartition_plan(list(reversed(keys)), 4, 3)
    check(plan_a == plan_b,
          "the re-partition plan must not depend on key iteration order")
    plan, moved = plan_a
    check(all(plan[k][0] != plan[k][1] for k in moved)
          and all(plan[k][0] == plan[k][1]
                  for k in keys if k not in moved),
          "moved must be EXACTLY the keys whose owner changed")
    _, same = repartition_plan(keys, 4, 4)
    check(same == [], "an unchanged group size must move nothing")

    import pickle
    a = pickle.dumps(({0: "s0", "__quant_ef__/f32:0": "r0"}, "OPT"))
    b = pickle.dumps(({1: "s1"}, None))
    merged, opt = merge_shard_states([a, b])
    check(merged == {0: "s0", 1: "s1", "__quant_ef__/f32:0": "r0"}
          and opt == "OPT",
          "merge must be the disjoint union and keep the optimizer")
    blobs = repartition_shard_states([a, b], 3)
    check(len(blobs) == 3 and len(set(blobs)) == 1
          and blobs == repartition_shard_states([a, b], 3),
          "re-partition must hand every new updater one identical "
          "deterministic merged blob")


def _parity(check):
    from .harness import SimulatedCluster

    base = SimulatedCluster(3).run(6)
    check(base.digests_agree(),
          "unfaulted baseline must keep one digest per step")

    c = SimulatedCluster(3)
    c.run(2)
    c.kill(1)
    c.run(2)
    c.rejoin(1)
    c.run(2)
    check(sorted(c.epochs_seen) == [0, 1, 2],
          "kill + rejoin must fence exactly two membership epochs "
          "(got %r)" % sorted(c.epochs_seen))
    check(c.digests_agree(),
          "virtual lockstep digests must agree on every step across "
          "the membership epochs (zero divergence)")
    check(c.loss_trajectory == base.loss_trajectory,
          "the faulted run's loss trajectory must be byte-identical "
          "to the unfaulted baseline")
    check(base.params_bytes() == c.params_bytes(),
          "final params must be byte-identical to the baseline")
    check(c.params_bytes(1) == c.params_bytes(0),
          "the rejoined rank must hold the survivors' exact bytes")


def _ps_stream(check):
    from ..parallel import ps
    from ..armor import checkpoint as ckpt
    from ..armor.errors import CheckpointCorruptError
    from .harness import SimulatedCluster
    from .rejoin import stream_snapshot, fetch_snapshot, _keys

    cluster = SimulatedCluster(2).run(1)
    donor = cluster.live[0]
    state = ckpt.snapshot_trainer(donor.trainer, cluster.step_count)

    srv = ps.ParameterServer(host="127.0.0.1")
    client = ps.PSClient(srv.address)
    fd, tmp = tempfile.mkstemp(suffix=".armor")
    os.close(fd)
    try:
        ckpt.save_state(tmp, state)
        raw_want = open(tmp, "rb").read()
        os.environ["GRAFT_BUCKET_BYTES"] = str(64 << 10)   # force chunking
        manifest = stream_snapshot(client, tmp, "wire-test")
        check(manifest["nbytes"] == len(raw_want),
              "stream manifest must carry the exact payload size")
        raw_got = fetch_snapshot(client, "wire-test", timeout=5.0)
        check(raw_got == raw_want,
              "a PS-wire streamed snapshot must round-trip bit-exactly")

        # mangled stream: good manifest, torn chunk bytes
        import json
        mkey, ckeys = _keys("wire-torn", 1)
        client.init({ckeys[0]: np.frombuffer(raw_want[:-8], np.uint8)})
        client.init({mkey: np.frombuffer(json.dumps(
            {"nchunks": 1, "nbytes": len(raw_want),
             "sha256": manifest["sha256"], "tag": "wire-torn"},
            sort_keys=True).encode(), np.uint8)})
        try:
            fetch_snapshot(client, "wire-torn", timeout=5.0)
            check(False, "a torn stream must not validate")
        except CheckpointCorruptError:
            pass
    finally:
        os.environ.pop("GRAFT_BUCKET_BYTES", None)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        client.close()
        srv.shutdown()


def _chaos(check):
    from ..armor import faults
    from ..armor.errors import (CollectiveTimeoutError, FaultInjectedError,
                                QuiesceTimeoutError)
    from .membership import Membership, MembershipView
    from .rejoin import InProcessByteStore, fetch_snapshot

    # a stream that never appears: typed timeout inside the budget
    faults.configure("membership.join:drop")
    t0 = time.perf_counter()
    try:
        fetch_snapshot(InProcessByteStore(), "never", timeout=0.3)
        check(False, "an absent stream must raise the typed timeout")
    except CollectiveTimeoutError as exc:
        check(exc.site == "membership.join" and exc.timeout_s == 0.3,
              "stream timeout must name the join site and its budget")
    check(time.perf_counter() - t0 < 5.0,
          "the join poll must respect its budget, not spin forever")

    # seeded join chaos replays identically
    def join_verdicts(n):
        faults.configure("membership.join:error:p=0.5:seed=11:times=100")
        out = []
        store = InProcessByteStore()
        store.init({"__elastic__/snap/t/manifest": np.zeros(1, np.uint8)})
        for _ in range(n):
            try:
                faults.fault_point("membership.join", tag="t")
                out.append(False)
            except FaultInjectedError:
                out.append(True)
        return out
    seq = join_verdicts(16)
    check(seq == join_verdicts(16) and any(seq) and not all(seq),
          "seeded membership.join chaos must replay deterministically")

    # a dropped re-partition leaves the rank on the OLD epoch — the
    # divergence the lockstep auditor names
    faults.configure("membership.repartition:drop:times=1")
    launch = MembershipView(0, range(3))
    lag, ok = Membership(0, view=launch), Membership(2, view=launch)
    for m in (lag, ok):
        m.request_change(departed=[1])
    lag.apply_pending()
    ok.apply_pending()
    check(lag.epoch == 0 and ok.epoch == 1,
          "a dropped re-partition must leave ONLY that rank on the old "
          "epoch (got %d/%d)" % (lag.epoch, ok.epoch))
    faults.reset()
    lag.apply_pending()
    check(lag.epoch == 0 and not lag.pending(),
          "the dropped change must not replay later on its own")

    # a stuck duplex wire: quiesce raises typed, keeps ownership
    from concurrent.futures import Future
    from ..parallel.dist import DistKVStore
    kv = object.__new__(DistKVStore)
    stuck = Future()
    kv._push_futs = [stuck]
    kv._pull_pool = None
    try:
        kv.quiesce(timeout=0.05)
        check(False, "an undrainable wire must raise QuiesceTimeoutError")
    except QuiesceTimeoutError as exc:
        check(exc.pending == 1 and exc.site == "kvstore.quiesce",
              "quiesce timeout must name the site and pending count")
    check(kv._push_futs == [stuck],
          "undrained futures must stay owned after a quiesce timeout")
    stuck.set_result(None)
    check(kv.quiesce(timeout=1.0) == 1 and kv._push_futs == [],
          "a settled wire must drain and report the drained count")


def _trainer(seed=3):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from .. import random_state
    random_state.seed(seed)
    net = gluon.nn.Dense(4, prefix="elastic_selftest_")
    net.initialize(ctx=mx.cpu())
    rs = np.random.RandomState(seed)
    net(mx.nd.array(rs.randn(2, 6).astype(np.float32)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer, rs


def _step(net, trainer, rs):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd
    x = mx.nd.array(rs.randn(2, 6).astype(np.float32))
    with autograd.record():
        loss = (net(x) * net(x)).sum()
    loss.backward()
    trainer.step(2)


def _shard_repartition(check):
    from . import set_enabled
    from ..armor import checkpoint as ckpt
    from ..armor.errors import ShardOwnershipError

    def snap_with_spec(n, rank):
        net, trainer, rs = _trainer()
        _step(net, trainer, rs)      # momentum state materializes
        trainer._zero_spec = lambda: {"axis": "ctx", "n": n, "rank": rank}
        return net, trainer, ckpt.snapshot_trainer(trainer, 7)

    for old_n, new_n in ((2, 4), (4, 2)):     # grow AND shrink
        _, _, state = snap_with_spec(old_n, 0)
        check(state.get("shard", {}).get("n") == old_n
              and "membership_epoch" in state,
              "a ZeRO snapshot must carry its shard spec and epoch")
        net2, t2, rs2 = _trainer()
        t2._zero_spec = lambda: {"axis": "ctx", "n": new_n, "rank": 1}
        set_enabled(False)
        try:
            ckpt.restore_trainer(t2, state)
            check(False, "restore across %d->%d shards with elastic OFF "
                  "must refuse" % (old_n, new_n))
        except ShardOwnershipError as exc:
            check(exc.epoch is not None
                  and "GRAFT_ELASTIC" in str(exc),
                  "the refusal must name the snapshot epoch and the "
                  "GRAFT_ELASTIC remedy")
        set_enabled(True)
        step = ckpt.restore_trainer(t2, state)
        check(step == 7,
              "restore across %d->%d shards with elastic ON must "
              "re-partition and land on the saved step" % (old_n, new_n))
        want = {n: np.asarray(p.data()._read()).tobytes()
                for n, p in net2.collect_params().items()}
        _, _, state_b = snap_with_spec(old_n, 0)
        net3, t3, _ = _trainer()
        t3._zero_spec = lambda: {"axis": "ctx", "n": new_n, "rank": 1}
        ckpt.restore_trainer(t3, state_b)
        check({n: np.asarray(p.data()._read()).tobytes()
               for n, p in net3.collect_params().items()} == want,
              "the elastic re-partition must be deterministic "
              "(two replays, identical bytes)")
    set_enabled(None)


def _inert(check):
    from . import enabled, set_enabled
    os.environ.pop("GRAFT_ELASTIC", None)
    set_enabled(None)
    check(enabled() is False,
          "GRAFT_ELASTIC unset must leave elastic off")
    os.environ["GRAFT_ELASTIC"] = "1"
    check(enabled() is True, "GRAFT_ELASTIC=1 must enable elastic")
    os.environ["GRAFT_ELASTIC"] = "0"
    check(enabled() is False, "GRAFT_ELASTIC=0 must disable elastic")
    set_enabled(True)
    check(enabled() is True, "set_enabled must override the env")
    set_enabled(None)

    # the step fence: a pending change on a DISABLED trainer must not
    # apply inside step() (bit-identical inert contract)
    from .membership import Membership
    net, trainer, rs = _trainer()
    m = Membership(0, world_size=3)
    trainer.attach_membership(m)
    m.request_change(departed=[2])
    _step(net, trainer, rs)
    check(m.epoch == 0 and m.pending(),
          "with elastic OFF, step() must not touch the pending change")
    set_enabled(True)
    _step(net, trainer, rs)
    check(m.epoch == 1 and not m.pending(),
          "with elastic ON, step() must fence the pending change")
    set_enabled(None)


def selftest():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ..analysis import lockstep
    from ..armor import faults
    from ..telemetry import blackbox

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)
            print("graftelastic selftest FAIL: %s" % msg, file=sys.stderr)

    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    prev_enabled = blackbox._enabled_override
    blackbox.set_enabled(True)
    try:
        _membership_algebra(check)
        _parity(check)
        _ps_stream(check)
        _chaos(check)
        _shard_repartition(check)
        _inert(check)
    finally:
        faults.reset()
        lockstep.reset()
        blackbox.set_enabled(prev_enabled)
        from . import set_enabled
        set_enabled(None)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if failures:
        print("graftelastic selftest: %d failure(s)" % len(failures),
              file=sys.stderr)
        return 1
    print("graftelastic selftest OK (membership algebra pure, kill+rejoin "
          "byte parity across 2 epochs, PS-wire stream validated, chaos "
          "deterministic + typed timeouts, shard re-partition both "
          "directions, GRAFT_ELASTIC=0 inert)")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m incubator_mxnet_tpu.elastic")
    ap.add_argument("--selftest", action="store_true")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
