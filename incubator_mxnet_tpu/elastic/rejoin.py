"""Checkpoint-streamed rejoin (graftelastic).

A replacement rank does not replay history — it streams the newest
VALIDATED armor snapshot over the PS wire and joins at the next epoch
fence.  The stream is the armor file format verbatim (magic + sha256 +
length + payload, armor/checkpoint.py): a survivor chunks the snapshot
bytes into ~``GRAFT_BUCKET_BYTES`` uint8 buckets and ``init``s them
under tagged ``__elastic__/snap/<tag>/…`` keys next to a manifest
carrying the chunk count and payload sha256; the joiner polls for the
manifest, pulls the chunks, re-hashes, and loads through the normal
``load_state`` validation — a torn or corrupt stream surfaces as the
same typed :class:`~..armor.errors.CheckpointCorruptError` a corrupt
file would.  Because the snapshot already captures the optimizer-shard
blobs and ``__quant_ef__`` residuals (PR 19), the departed rank's
exclusive state rides the same stream with no extra machinery.

Chaos site ``membership.join`` fires once per fetch attempt: ``drop``
makes that poll find nothing (the joiner retries until its
``GRAFT_REJOIN_TIMEOUT`` budget expires), ``delay``/``error`` behave
as everywhere else.

The byte-store interface is the PSClient verb subset ``init(dict)`` /
``pull(keys) -> dict`` / ``stat(keys) -> dict`` — a real
:class:`~..parallel.ps.PSClient` works verbatim, and
:class:`InProcessByteStore` supplies the same verbs for the
single-process harness and tests.
"""
from __future__ import annotations

import hashlib
import json
import os
import time

import numpy as np

__all__ = ["InProcessByteStore", "stream_snapshot", "fetch_snapshot",
           "rejoin_trainer", "rejoin_timeout", "chunk_bytes",
           "SNAP_PREFIX"]

SNAP_PREFIX = "__elastic__/snap"


def rejoin_timeout():
    """GRAFT_REJOIN_TIMEOUT in seconds (default 120): the joiner's
    whole-fetch budget — manifest poll + chunk pulls."""
    try:
        t = float(os.environ.get("GRAFT_REJOIN_TIMEOUT", "120"))
    except ValueError:
        return 120.0
    return t if t > 0 else 120.0


def chunk_bytes():
    """Stream chunk size: GRAFT_BUCKET_BYTES (the same knob that sizes
    gradient buckets — the snapshot rides the wire in the same units),
    floor 64 KiB."""
    try:
        n = int(os.environ.get("GRAFT_BUCKET_BYTES", str(4 << 20)))
    except ValueError:
        n = 4 << 20
    return max(n, 64 << 10)


class InProcessByteStore(object):
    """The PSClient verb subset over a plain dict — the harness/test
    stand-in for a real parameter-server client (first-write-wins init,
    copy-out pull, presence-only stat; same semantics as the server's
    dispatch switch)."""

    def __init__(self):
        self._store = {}

    def init(self, kv):
        for k, v in kv.items():
            self._store.setdefault(k, np.array(v))

    def pull(self, keys):
        return {k: self._store[k].copy() for k in keys}

    def stat(self, keys):
        return {k: (tuple(self._store[k].shape), str(self._store[k].dtype))
                for k in keys if k in self._store}


def _keys(tag, n_chunks=None):
    manifest = "%s/%s/manifest" % (SNAP_PREFIX, tag)
    if n_chunks is None:
        return manifest
    return manifest, ["%s/%s/%06d" % (SNAP_PREFIX, tag, i)
                      for i in range(n_chunks)]


def stream_snapshot(client, path, tag):
    """Publish one armor snapshot file onto the byte store under
    ``tag`` (conventionally the fence epoch — PS ``init`` is
    first-write-wins, so each epoch's stream needs its own tag).
    Returns the manifest dict."""
    with open(path, "rb") as f:
        raw = f.read()
    csize = chunk_bytes()
    chunks = [raw[i:i + csize] for i in range(0, len(raw), csize)] or [b""]
    manifest = {"nchunks": len(chunks), "nbytes": len(raw),
                "sha256": hashlib.sha256(raw).hexdigest(),
                "tag": str(tag)}
    mkey, ckeys = _keys(tag, len(chunks))
    kv = {k: np.frombuffer(c, dtype=np.uint8)
          for k, c in zip(ckeys, chunks)}
    # manifest LAST: its presence is the joiner's ready signal
    client.init(kv)
    client.init({mkey: np.frombuffer(
        json.dumps(manifest, sort_keys=True).encode(), dtype=np.uint8)})
    from ..telemetry import blackbox as _blackbox
    _blackbox.record("snapshot_streamed", tag=str(tag),
                     nbytes=len(raw), nchunks=len(chunks))
    return manifest


def fetch_snapshot(client, tag, timeout=None):
    """Pull + validate one streamed snapshot; returns the raw armor
    file bytes.  Polls for the manifest until ``timeout`` (default
    ``GRAFT_REJOIN_TIMEOUT``) and raises the typed
    :class:`~..armor.errors.CollectiveTimeoutError` when the stream
    never appears; a hash mismatch raises
    :class:`~..armor.errors.CheckpointCorruptError` (stream identity,
    not availability)."""
    from ..armor import faults as _faults
    from ..armor.errors import (CheckpointCorruptError,
                                CollectiveTimeoutError)
    budget = rejoin_timeout() if timeout is None else float(timeout)
    mkey = _keys(tag)
    t0 = time.monotonic()
    delay = 0.01
    while True:
        verdict = _faults.fault_point("membership.join", tag=str(tag))
        present = verdict not in ("drop", "disconnect") \
            and client.stat([mkey]).get(mkey) is not None
        if present:
            break
        age = time.monotonic() - t0
        if age >= budget:
            raise CollectiveTimeoutError(
                "membership.join", age, budget,
                detail="snapshot stream %r never appeared" % str(tag))
        time.sleep(min(delay, budget - age))
        delay = min(delay * 2, 0.25)
    manifest = json.loads(client.pull([mkey])[mkey].tobytes().decode())
    _, ckeys = _keys(tag, int(manifest["nchunks"]))
    fetched = client.pull(ckeys)
    raw = b"".join(fetched[k].tobytes() for k in ckeys)
    if len(raw) != int(manifest["nbytes"]) \
            or hashlib.sha256(raw).hexdigest() != manifest["sha256"]:
        raise CheckpointCorruptError(
            "<stream:%s>" % tag, "streamed payload fails its manifest "
            "hash (%d of %d bytes)" % (len(raw), manifest["nbytes"]))
    return raw


def rejoin_trainer(trainer, client, tag, membership=None, view=None,
                   timeout=None):
    """The joiner's whole flow: fetch the streamed snapshot, validate,
    restore onto ``trainer``, adopt the fence ``view`` on
    ``membership`` (re-basing the lockstep stream at the fence epoch).
    Returns the restored step."""
    import tempfile
    from ..armor import checkpoint as _ckpt
    from ..telemetry import blackbox as _blackbox
    from ..telemetry import metrics as _tmetrics
    t0 = time.perf_counter()
    raw = fetch_snapshot(client, tag, timeout=timeout)
    fd, tmp = tempfile.mkstemp(suffix=".armor")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(raw)
        state = _ckpt.load_state(tmp)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    step = _ckpt.restore_trainer(trainer, state)
    if membership is not None and view is not None:
        membership.adopt(view)
    seconds = time.perf_counter() - t0
    _tmetrics.elastic_rejoin_seconds(seconds, nbytes=len(raw))
    _blackbox.record("membership_rejoin", tag=str(tag), step=step,
                     nbytes=len(raw), seconds=round(seconds, 6))
    return step
