"""Single-process simulated-N-rank membership harness (graftelastic).

The container this repo grows in has no multi-host CPU collective
transport, and the ROADMAP forbids shipping a dist feature whose only
"coverage" is a SKIP-MULTIPROC sentinel.  This harness gives elastic
logic REAL coverage in one process: ``n`` **virtual ranks**, each with
its own parameter replicas, its own ``gluon.Trainer``, its own
:class:`~.membership.Membership` state machine, and its own lockstep
fold stream (maintained through the auditor's pure
:func:`~..analysis.lockstep.fold_value` arithmetic, so the digests are
bit-comparable with the real module stream).

Determinism model — the property the byte-parity gate rests on: the
global batch is split into fixed **data shards**; shard → rank
ownership is a pure function of the membership view
(``view.ranks[shard % world_size]``), and the simulated allreduce sums
per-shard gradients **in shard-id order, never rank order**.  A
membership change moves WHO computes a shard, not WHAT is summed or in
what order — so a run that loses and regains a rank mid-training
reproduces the unfaulted run's loss trajectory byte-for-byte.  That is
the same discipline the real wire keeps (bucket content and issue
order are functions of the plan, not the rank), enforced here exactly.

Kill is abrupt (the rank object is dropped, as ``os._exit`` would);
survivors queue the departure and apply it behind the next step fence.
Rejoin streams a fresh armor snapshot through a byte store (the
in-process one by default; a real ``PSClient`` works verbatim — the
selftest runs one) and the joiner adopts the fence view.
"""
from __future__ import annotations

import os
import tempfile

import numpy as np

from . import membership as _membership
from . import rejoin as _rejoin
from ..analysis import lockstep as _lockstep

__all__ = ["SimulatedRank", "SimulatedCluster", "shard_owner"]


def shard_owner(shard, view):
    """The live rank owning data shard ``shard`` under ``view`` — pure
    in ``(shard, view)``, so every survivor derives the same map."""
    return view.ranks[int(shard) % view.world_size]


class SimulatedRank(object):
    """One virtual rank: net + trainer + membership + fold stream."""

    def __init__(self, rid, cluster):
        self.rid = int(rid)
        self.net, self.trainer = cluster._build()
        self.membership = _membership.Membership(
            rank=rid, view=cluster.launch_view)
        self.folds = 0
        self.rolling = _lockstep.epoch_base(cluster.launch_view.epoch)
        self.trainer.attach_membership(self.membership)

    # -- the virtual auditor stream -----------------------------------------
    def fold(self, path, n_keys, nbytes):
        self.folds += 1
        self.rolling = _lockstep.fold_value(self.rolling, self.folds,
                                            path, n_keys, nbytes)
        return self.rolling

    def rebase(self, epoch):
        self.folds = 0
        self.rolling = _lockstep.epoch_base(epoch)

    def digest(self):
        return (self.membership.epoch, self.folds, self.rolling)


class SimulatedCluster(object):
    """``n`` virtual ranks stepping one replicated model in lockstep.

    ``step()`` runs one fenced training step: apply queued membership
    changes, compute per-shard gradients on their owners, sum them in
    shard order (the simulated allreduce), fold the collective into
    every live rank's auditor stream, and apply the identical update on
    every replica.  ``kill``/``rejoin`` drive membership changes; the
    loss-trajectory bytes and per-step digests accumulate on the
    instance for parity assertions."""

    def __init__(self, n_ranks, batch=2, dim=6, units=4, n_shards=None,
                 model_seed=11, data_seed=23, lr=0.1, momentum=0.9):
        self.n0 = int(n_ranks)
        self.batch = int(batch)
        self.dim = int(dim)
        self.units = int(units)
        self.n_shards = int(n_shards) if n_shards else 2 * self.n0
        self.model_seed = int(model_seed)
        self.lr = lr
        self.momentum = momentum
        self.launch_view = _membership.MembershipView(0, range(self.n0))
        self._data = np.random.RandomState(int(data_seed))
        self.step_count = 0
        self.loss_trajectory = []       # raw float32 bytes per step
        self.digest_history = []        # per-step tuple of rank digests
        self.epochs_seen = set([0])
        self.live = {}
        for rid in range(self.n0):
            self.live[rid] = SimulatedRank(rid, self)

    # -- model construction -------------------------------------------------
    def _build(self):
        """One deterministic replica: global-RNG-seeded init, so every
        rank (and every run) starts from identical bytes."""
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu import gluon
        from .. import random_state
        random_state.seed(self.model_seed)
        # fixed prefix: gluon's global name counter would otherwise give
        # each replica different param names, and a streamed snapshot
        # restores BY NAME
        net = gluon.nn.Dense(self.units, prefix="elastic_dense_")
        net.initialize(ctx=mx.cpu())
        net(mx.nd.array(np.zeros((self.batch, self.dim), np.float32)))
        trainer = gluon.Trainer(
            net.collect_params(), "sgd",
            {"learning_rate": self.lr, "momentum": self.momentum})
        return net, trainer

    def params_bytes(self, rid=None):
        rank = self.live[rid if rid is not None
                         else min(self.live)]
        return {name: np.asarray(p.data()._read()).tobytes()
                for name, p in rank.net.collect_params().items()}

    # -- membership ---------------------------------------------------------
    def view(self):
        return self.live[min(self.live)].membership.view

    def kill(self, rid):
        """Abrupt rank death: the rank is gone NOW; survivors learn at
        the next step fence (the dead-node table naming it)."""
        del self.live[rid]
        for r in self.live.values():
            r.membership.request_change(departed=[rid])

    def rejoin(self, rid, store=None):
        """Checkpoint-streamed rejoin of ``rid``: a survivor snapshots
        at the fence, streams it through ``store`` (the in-process byte
        store unless a PSClient-shaped one is given), the replacement
        restores + adopts the fence view, and survivors queue the join
        for their next fence."""
        from ..armor import checkpoint as _ckpt
        store = store if store is not None else _rejoin.InProcessByteStore()
        donor = self.live[min(self.live)]
        fence = donor.membership.view.advance(joined=[rid])
        state = _ckpt.snapshot_trainer(donor.trainer, self.step_count)
        fd, tmp = tempfile.mkstemp(suffix=".armor")
        os.close(fd)
        try:
            _ckpt.save_state(tmp, state)
            tag = "epoch-%d" % fence.epoch
            _rejoin.stream_snapshot(store, tmp, tag)
            newr = SimulatedRank(rid, self)
            step = _rejoin.rejoin_trainer(
                newr.trainer, store, tag,
                membership=newr.membership, view=fence)
        finally:
            for p in (tmp, tmp + ".manifest.json"):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        newr.rebase(fence.epoch)
        for r in self.live.values():
            r.membership.request_change(joined=[rid])
        self.live[rid] = newr
        return step

    # -- one fenced training step -------------------------------------------
    def _fence(self):
        """Apply queued membership changes on every live rank, re-base
        their virtual fold streams on an epoch move, and assert the
        survivors converged on ONE view."""
        for r in self.live.values():
            before = r.membership.epoch
            applied = r.membership.apply_pending(trainer=r.trainer,
                                                 kv=None)
            if applied is not None and applied.epoch != before:
                r.rebase(applied.epoch)
        views = {r.membership.view for r in self.live.values()}
        if len(views) != 1:
            raise AssertionError("ranks disagree on the membership view "
                                 "after the fence: %r" % views)
        view = views.pop()
        self.epochs_seen.add(view.epoch)
        return view

    def _shard_grads(self, rank, x):
        """One shard's gradients on its owner, as numpy, plus the shard
        loss (float32)."""
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu import autograd
        xs = mx.nd.array(x)
        with autograd.record():
            out = rank.net(xs)
            loss = (out * out).sum()
        loss.backward()
        grads = [np.asarray(p.grad()._read()).copy()
                 for _n, p in sorted(rank.net.collect_params().items())]
        return grads, np.float32(np.asarray(loss._read()))

    def step(self):
        """One fenced, shard-ordered, replicated training step.
        Returns the step's global loss (float32)."""
        import incubator_mxnet_tpu as mx
        view = self._fence()
        shards = [self._data.randn(self.batch, self.dim).astype(np.float32)
                  for _ in range(self.n_shards)]
        summed = None
        loss = np.float32(0)
        for s, x in enumerate(shards):
            owner = self.live[shard_owner(s, view)]
            grads, l = self._shard_grads(owner, x)
            loss = np.float32(loss + l)
            if summed is None:
                summed = grads
            else:
                summed = [np.add(a, g, dtype=a.dtype)
                          for a, g in zip(summed, grads)]
        nbytes = sum(int(g.nbytes) for g in summed)
        digests = []
        for rid in sorted(self.live):
            r = self.live[rid]
            r.fold("reduce_many", len(summed), nbytes)
            digests.append(r.digest())
        self.digest_history.append(tuple(digests))
        for rid in sorted(self.live):
            r = self.live[rid]
            params = [p for _n, p in
                      sorted(r.net.collect_params().items())]
            for p, g in zip(params, summed):
                p.grad()[:] = mx.nd.array(g)
            r.trainer.step(self.batch * self.n_shards)
        self.step_count += 1
        self.loss_trajectory.append(loss.tobytes())
        return loss

    def run(self, n_steps):
        for _ in range(n_steps):
            self.step()
        return self

    def digests_agree(self):
        """True when every recorded step's live ranks reported one
        identical (epoch, folds, rolling) digest — the harness's
        zero-divergence assertion."""
        return all(len(set(row)) == 1 for row in self.digest_history)
