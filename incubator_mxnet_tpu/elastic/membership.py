"""Epoch-fenced membership + deterministic re-partition (graftelastic).

The contract, in one paragraph: cluster membership is a sequence of
**epochs**.  Epoch 0 is the launch membership; every change (a rank
named dead by the heartbeat table, a replacement rejoining) advances
the epoch by exactly one and is applied by every survivor **behind the
same step barrier** — queued on :class:`Membership`, drained by the
Trainer's step fence — so no two ranks ever run a step under different
views.  Everything derived from membership (PS key owners, ZeRO
``shard_owners``, bucket/duplex plans, the lockstep fold stream) is a
pure function of the new view, recomputed locally by each survivor
with no coordinator: determinism IS the consensus protocol.

Chaos sites (``GRAFT_FAULTS`` grammar, no grammar change needed):

* ``membership.repartition`` — fired once per applied change on every
  rank; ``drop`` skips the change (the rank keeps the old view — the
  lockstep auditor then names it, which is the point), ``delay``/
  ``error`` behave as everywhere else.
* ``membership.join`` lives in :mod:`.rejoin`.
"""
from __future__ import annotations

import threading
import zlib
from collections import deque

import pickle

__all__ = ["MembershipView", "Membership", "key_owner",
           "repartition_plan", "merge_shard_states",
           "repartition_shard_states"]


class MembershipView(object):
    """One immutable membership epoch: ``epoch``, the sorted tuple of
    live ``ranks``, and the delta (``departed``/``joined``) that
    produced it.  Two survivors computing the next view from the same
    inputs get equal views — compare with ``==``."""

    __slots__ = ("epoch", "ranks", "departed", "joined")

    def __init__(self, epoch, ranks, departed=(), joined=()):
        self.epoch = int(epoch)
        self.ranks = tuple(sorted(int(r) for r in ranks))
        self.departed = tuple(sorted(int(r) for r in departed))
        self.joined = tuple(sorted(int(r) for r in joined))

    @property
    def world_size(self):
        return len(self.ranks)

    def advance(self, departed=(), joined=()):
        """The NEXT view after removing ``departed`` and adding
        ``joined`` — pure, so every survivor derives the same epoch
        ``self.epoch + 1`` view."""
        dead = set(int(r) for r in departed)
        new = set(int(r) for r in joined)
        ranks = (set(self.ranks) - dead) | new
        if not ranks:
            raise ValueError("membership change would leave zero ranks")
        return MembershipView(self.epoch + 1, ranks,
                              departed=dead & set(self.ranks),
                              joined=new - set(self.ranks))

    def __eq__(self, other):
        return (isinstance(other, MembershipView)
                and self.epoch == other.epoch
                and self.ranks == other.ranks)

    def __ne__(self, other):
        return not self.__eq__(other)

    def __hash__(self):
        return hash((self.epoch, self.ranks))

    def __repr__(self):
        return ("MembershipView(epoch=%d, ranks=%r, departed=%r, "
                "joined=%r)" % (self.epoch, self.ranks, self.departed,
                                self.joined))

    def as_dict(self):
        return {"epoch": self.epoch, "ranks": list(self.ranks),
                "departed": list(self.departed),
                "joined": list(self.joined),
                "world_size": self.world_size}


# -- deterministic re-partition helpers -------------------------------------

def key_owner(key, n_servers):
    """The server owning ``key`` in an ``n_servers`` group — the exact
    placement hash the PS wire uses (``GroupClient._shard_of``:
    ``crc32(str(key)) % N``), exposed so re-partition plans and the PS
    client can never disagree about where a key lives."""
    if n_servers <= 0:
        raise ValueError("n_servers must be positive")
    return zlib.crc32(str(key).encode()) % int(n_servers)


def repartition_plan(keys, old_n, new_n):
    """The key-movement plan for a server-group resize: ``{key: (old
    owner, new owner)}`` plus the list of keys whose owner CHANGED
    (the only ones whose bytes must move).  Pure — every survivor
    computes the identical plan."""
    plan = {k: (key_owner(k, old_n), key_owner(k, new_n)) for k in keys}
    moved = sorted((k for k, (a, b) in plan.items() if a != b), key=str)
    return plan, moved


def merge_shard_states(shard_blobs):
    """Merge ZeRO-1 optimizer-shard blobs (the pickled
    ``Updater.get_states(dump_optimizer=True)`` payloads an armor
    snapshot carries in ``optimizer_shards``) into ONE
    ``(states, optimizer)`` pair.  Ownership is exclusive — each
    int-keyed per-param state and each ``__quant_ef__`` residual lives
    in exactly one shard — so the merge is a disjoint union; iteration
    order is blob order, making the (theoretical) overlap rule
    deterministic: later shards win."""
    merged = {}
    optimizer = None
    for blob in shard_blobs:
        payload = pickle.loads(blob)
        if isinstance(payload, tuple) and len(payload) == 2:
            states, opt = payload
            if opt is not None:
                optimizer = opt
        else:
            states = payload
        merged.update(states)
    return merged, optimizer


def repartition_shard_states(shard_blobs, new_n):
    """Deterministically re-partition saved optimizer-shard blobs for a
    CHANGED world size: merge every saved shard, then hand each of the
    ``new_n`` new updaters the full merged state dict.  Ownership under
    ZeRO-1 is *lazy* — an updater context-syncs (rehydrates) only the
    indices the new ``shard_owners`` bucket map assigns it, at its
    first fused update; unowned leaves stay host-side numpy and are
    never uploaded — so shipping the merged dict to every new owner IS
    the deterministic re-partition, without needing the bucket plan
    (which does not exist until the first post-restore step).  Returns
    ``new_n`` pickled blobs in ``set_states`` wire format."""
    merged, optimizer = merge_shard_states(shard_blobs)
    payload = (merged, optimizer) if optimizer is not None else merged
    blob = pickle.dumps(payload)
    return [blob] * int(new_n)


# -- the per-rank state machine ---------------------------------------------

class Membership(object):
    """One rank's membership state machine.

    Changes are **queued** (:meth:`request_change` — typically from the
    heartbeat dead-node observer or a supervisor) and **applied** at
    the step fence (:meth:`apply_pending`, called by ``Trainer.step``
    when ``GRAFT_ELASTIC=1``, or directly by harnesses), so a
    re-partition can never land mid-collective.  Applying a change:

    1. fires the ``membership.repartition`` chaos site,
    2. quiesces the store's duplex wire (``kv.quiesce()`` — satellite
       fix: in-flight async pushes/pulls drain with a typed timeout
       BEFORE any key range moves),
    3. advances the view (pure), re-bases the lockstep fold stream at
       the new epoch,
    4. invalidates the trainer's bucket/duplex plans and notifies its
       ``on_membership_change`` callbacks,
    5. journals a ``membership_epoch`` flight-recorder event and bumps
       the ``graft_elastic_*`` metrics.
    """

    def __init__(self, rank, world_size=None, view=None):
        if view is None:
            view = MembershipView(0, range(int(world_size)))
        self.rank = int(rank)
        self.view = view
        self._pending = deque()
        self._lock = threading.Lock()

    @property
    def epoch(self):
        return self.view.epoch

    def request_change(self, departed=(), joined=()):
        """Queue one membership change for the next step fence."""
        with self._lock:
            self._pending.append((tuple(departed), tuple(joined)))

    def pending(self):
        return bool(self._pending)

    def adopt(self, view):
        """Adopt an externally-derived view verbatim (the rejoin path:
        the replacement rank takes the fence epoch it streamed in at
        rather than replaying the survivors' change history)."""
        from ..analysis import lockstep as _lockstep
        with self._lock:
            self.view = view
            self._pending.clear()
        _lockstep.rebase(view.epoch)

    def apply_pending(self, trainer=None, kv=None):
        """Drain the queue (the step-fence entry point).  Returns the
        final view when anything was applied, else None."""
        applied = None
        while True:
            with self._lock:
                if not self._pending:
                    return applied
                departed, joined = self._pending.popleft()
            applied = self._apply(departed, joined, trainer, kv)

    # -- internals ----------------------------------------------------------
    def _apply(self, departed, joined, trainer, kv):
        from ..armor import faults as _faults
        from ..analysis import lockstep as _lockstep
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import metrics as _tmetrics
        new = self.view.advance(departed=departed, joined=joined)
        verdict = _faults.fault_point(
            "membership.repartition", epoch=new.epoch,
            departed=",".join(str(r) for r in new.departed),
            joined=",".join(str(r) for r in new.joined))
        if verdict in ("drop", "disconnect"):
            # this rank skips the re-partition: it keeps the old view on
            # purpose — the lockstep auditor's epoch-seeded streams then
            # name it as the diverged rank (chaos proves the detector)
            return self.view
        quiesce = getattr(kv, "quiesce", None)
        if quiesce is not None:
            quiesce()
        old_epoch = self.view.epoch
        self.view = new
        _lockstep.rebase(new.epoch)
        if trainer is not None:
            changed = getattr(trainer, "_membership_changed", None)
            if changed is not None:
                changed(new)
        _blackbox.record("membership_epoch", rank=self.rank,
                         old_epoch=old_epoch, **new.as_dict())
        _tmetrics.elastic_epoch(new.epoch)
        _tmetrics.elastic_repartition(new.world_size)
        return new
