"""Data iterators (ref: python/mxnet/io.py, 958 LoC + src/io/).

The ``DataIter`` protocol (provide_data/provide_label/reset/next with
DataBatch of NDArrays + pad) is preserved verbatim so Module.fit and
training scripts port unchanged.  C++-registry iterators of the reference
(src/io/iter_*.cc, MXNET_REGISTER_IO_ITER) map to Python classes backed by
numpy/OpenCV host pipelines; the prefetcher is a thread (the reference's
dmlc ThreadedIter, iter_prefetcher.h).
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError
from .ndarray import NDArray
from . import ndarray as nd

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "CSVIter",
           "LibSVMIter", "MNISTIter", "ImageRecordIter", "PrefetchingIter",
           "ResizeIter", "issue_device_prefetch", "device_prefetch_enabled"]


def device_prefetch_enabled(override=None):
    """GRAFT_PREFETCH_DEVICE (default on): issue batch N+1's
    host→device transfer while batch N computes (graftduplex data
    satellite) — the same issue/wait split ``ReduceHandle`` gave the
    gradient wire, applied to H2D."""
    if override is not None:
        return bool(override)
    return os.environ.get("GRAFT_PREFETCH_DEVICE", "1").strip().lower() \
        not in ("0", "false", "no", "off")


def issue_device_prefetch(obj):
    """Issue ``jax.device_put`` for every NDArray reachable under
    ``obj`` (an NDArray, a list/tuple, or a DataBatch) toward its own
    context's device, under ``engine.offband()`` so an open bulk segment
    on the calling thread is neither joined nor flushed.  The transfer
    is an async dispatch: by the time the consumer first reads the
    batch, the bytes are already on (or moving to) the device — H2D
    rides under compute instead of serializing the first op of the next
    forward.  Arrays already committed to the right device are left
    untouched; placement errors degrade to a no-op (the consumer's
    ordinary read still works)."""
    from . import engine as _engine
    if isinstance(obj, DataBatch):
        issue_device_prefetch(obj.data)
        issue_device_prefetch(obj.label)
        return obj
    if isinstance(obj, (list, tuple)):
        for item in obj:
            issue_device_prefetch(item)
        return obj
    if not isinstance(obj, NDArray):
        return obj
    try:
        import jax
        with _engine.offband():
            v = obj._read()
            dev = obj._ctx.jax_device()
            devs = getattr(v, "devices", None)
            if devs is not None and devs() != {dev}:
                obj._write(jax.device_put(v, dev))
    except Exception:
        pass        # unknown placement / abstract value: nothing to move
    return obj


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data description (ref: io.py class DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        """ref: io.py DataDesc.get_batch_axis."""
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch(object):
    """One mini-batch (ref: io.py class DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), \
                "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), \
                "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return "{}: data shapes: {} label shapes: {}".format(
            self.__class__.__name__, data_shapes, label_shapes)


class DataIter(object):
    """Base iterator (ref: io.py class DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        import time as _time
        t0 = _time.perf_counter()
        batch = self.next()
        # pipeline throughput telemetry: batches_total counter +
        # batches/sec EWMA gauge per iterator class (graftscope), and
        # the blocked span feeds graftlens' per-step data_wait component
        from .telemetry import lens as _lens
        from .telemetry import metrics as _tmetrics
        _lens.io_wait(t0, _time.perf_counter())
        _tmetrics.io_batch(type(self).__name__)
        return batch

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize another iterator to `size` batches per epoch
    (ref: io.py class ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class _PrefetchError(object):
    """Producer-side exception carrier: re-raised at the consumer's next
    iter_next() so a corrupt record fails the training loop instead of
    dying silently on a daemon thread."""

    def __init__(self, exc):
        self.exc = exc


class PrefetchingIter(DataIter):
    """Threaded prefetch over base iterator(s), ``prefetch_buffer`` batches
    deep (ref: io.py class PrefetchingIter / src/io/iter_prefetcher.h —
    the dmlc ThreadedIter double buffer, generalized to a bounded queue
    so a bursty consumer can drain several batches without stalling)."""

    _STOP = object()

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_buffer=1):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.prefetch_buffer = max(int(prefetch_buffer), 1)
        self.current_batch = None
        self.started = True
        self._start_threads()

    def _start_threads(self):
        import queue
        self._queues = [queue.Queue(maxsize=self.prefetch_buffer)
                        for _ in range(self.n_iter)]
        self._stop_flags = [False] * self.n_iter
        self._exhausted = False

        # the closure must NOT capture self: the producer thread would
        # otherwise keep the iterator alive forever and __del__ cleanup
        # could never run
        def prefetch_func(it, q, flags, i):
            while not flags[i]:
                try:
                    batch = it.next()
                    if device_prefetch_enabled():
                        # H2D for the lookahead batch issues on THIS
                        # thread, riding under the consumer's compute
                        issue_device_prefetch(batch)
                except StopIteration:
                    batch = None
                except Exception as exc:   # surface errors at the consumer
                    batch = _PrefetchError(exc)
                while not flags[i]:
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if batch is None or isinstance(batch, _PrefetchError):
                    return  # epoch exhausted / failed; restarted by reset()
        self.prefetch_threads = [
            threading.Thread(target=prefetch_func,
                             args=(self.iters[i], self._queues[i],
                                   self._stop_flags, i), daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def _stop_threads(self):
        for i in range(self.n_iter):
            self._stop_flags[i] = True
        for i, t in enumerate(self.prefetch_threads):
            # drain so a producer blocked on a full queue can observe stop
            while t.is_alive():
                try:
                    self._queues[i].get_nowait()
                except Exception:
                    pass
                t.join(timeout=0.05)

    def __del__(self):
        try:
            self._stop_threads()
        except Exception:
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        self._stop_threads()
        for i in self.iters:
            i.reset()
        self._start_threads()

    def close(self):
        """Stop the producer threads and drop buffered batches.  Call when
        abandoning the iterator mid-epoch; reset() restarts after it."""
        self._stop_threads()
        self._exhausted = True  # iter_next() answers False, never blocks

    def iter_next(self):
        if self._exhausted:
            # the producer put ONE end-of-epoch sentinel and parked;
            # keep answering False (Event-era behavior) until reset()
            return False
        batches = [q.get() for q in self._queues]
        for b in batches:
            if isinstance(b, _PrefetchError):
                self._exhausted = True
                raise b.exc
        if batches[0] is None:
            self._exhausted = True
            for b in batches:
                assert b is None, "Number of entry mismatches between iterators"
            return False
        for batch in batches:
            assert batch.pad == batches[0].pad, \
                "Number of entry mismatches between iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in batches], []),
            sum([batch.label for batch in batches], []),
            batches[0].pad, batches[0].index,
            provide_data=self.provide_data, provide_label=self.provide_label)
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    """Convert data into a canonical [(name, array)] list (ref: io.py
    _init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them "
                        "or dict with them as values")
    ret = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            try:
                v = nd.array(v)
            except Exception:
                raise TypeError("Invalid type '%s' for %s, should be NDArray "
                                "or numpy.ndarray" % (type(v), k))
        ret.append((k, v))
    return list(sorted(ret))


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (ref: io.py:546 class NDArrayIter):
    shuffle, pad/discard/roll_over last-batch handling, multi-input dicts."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)

        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, nd.array(v.asnumpy()[self.idx], dtype=v.dtype))
                         for k, v in self.data]
            self.label = [(k, nd.array(v.asnumpy()[self.idx], dtype=v.dtype))
                          for k, v in self.label]

        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]

        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [x[1][self.cursor:self.cursor + self.batch_size]
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [nd.ndarray.concatenate([x[1][self.cursor:], x[1][:pad]])
                for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(DataIter):
    """CSV file iterator (ref: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.dtype(dtype),
                          ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32,
                               ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
        else:
            label = np.zeros((data.shape[0],) + tuple(label_shape), np.float32)
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="roll_over" if round_batch
                                  else "pad")
        # csv iter names (ref: iter_csv.cc uses data/label)
        self._inner.data = [("data", self._inner.data[0][1])]
        self._inner.label = [("label", self._inner.label[0][1])]

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM sparse-format iterator (ref: src/io/iter_libsvm.cc) — parses
    into CSR arrays (ndarray.sparse)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 batch_size=1, **kwargs):
        super().__init__(batch_size)
        from .ndarray import sparse as sp
        indptr = [0]
        indices = []
        values = []
        labels = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    indices.append(int(k))
                    values.append(float(v))
                indptr.append(len(indices))
        # keep the data CSR end-to-end: the reference never materializes
        # LibSVM rows densely (iter_libsvm.cc parses straight to
        # kCSRStorage) — an (n, dim) dense buffer would OOM at RCV1 scale
        self._values = np.array(values, np.float32)
        self._indices = np.array(indices, np.int64)
        self._indptr = np.array(indptr, np.int64)
        self._labels = np.array(labels, np.float32)
        self._dim = int(np.prod(data_shape))
        self._n = len(labels)
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._dim), "float32")]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,), "float32")]

    def reset(self):
        self._cursor = 0

    def next(self):
        """Batches carry CSR data (the reference's LibSVMIter yields
        kCSRStorage batches, iter_libsvm.cc) — sparse models feed
        mx.nd.sparse.dot without densifying.  Built by slicing the parsed
        CSR triple per batch; the tail batch pads by wrapping."""
        from .ndarray import sparse as sp
        if self._cursor >= self._n:
            raise StopIteration
        rows = [(self._cursor + i) % self._n
                for i in range(self.batch_size)]
        pad = max(self._cursor + self.batch_size - self._n, 0)
        self._cursor += self.batch_size
        data_parts, idx_parts, ptr = [], [], [0]
        for r in rows:
            lo, hi = self._indptr[r], self._indptr[r + 1]
            data_parts.append(self._values[lo:hi])
            idx_parts.append(self._indices[lo:hi])
            ptr.append(ptr[-1] + (hi - lo))
        csr = sp.csr_matrix(
            (np.concatenate(data_parts) if data_parts else
             np.zeros(0, np.float32),
             np.concatenate(idx_parts) if idx_parts else
             np.zeros(0, np.int64),
             np.array(ptr, np.int64)),
            shape=(self.batch_size, self._dim))
        from .ndarray import array as _arr
        labels = _arr(self._labels[rows])
        return DataBatch([csr], [labels], pad)


class MNISTIter(DataIter):
    """MNIST idx-file iterator (ref: src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, input_shape=None, **kwargs):
        super().__init__(batch_size)

        def _open(path):
            if path.endswith(".gz"):
                return gzip.open(path, "rb")
            return open(path, "rb")
        with _open(label) as fin:
            struct.unpack(">II", fin.read(8))
            lab = np.frombuffer(fin.read(), dtype=np.uint8).astype(np.float32)
        with _open(image) as fin:
            struct.unpack(">IIII", fin.read(16))
            img = np.frombuffer(fin.read(), dtype=np.uint8)
            img = img.reshape(len(lab), 28, 28).astype(np.float32) / 255.0
        if flat:
            img = img.reshape(len(lab), 784)
        else:
            img = img.reshape(len(lab), 1, 28, 28)
        if input_shape is not None:
            img = img.reshape((len(lab),) + tuple(input_shape))
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(len(lab))
            img, lab = img[order], lab[order]
        self._inner = NDArrayIter(img, lab, batch_size,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(path_imgrec=None, data_shape=None, batch_size=1,
                    label_width=1, shuffle=False, part_index=0, num_parts=1,
                    preprocess_threads=4, prefetch_buffer=4, **kwargs):
    """ImageRecordIter factory (ref: src/io/iter_image_recordio_2.cc:727
    registration). Returns a PrefetchingIter (``prefetch_buffer`` batches
    deep, background thread) over an image.ImageIter whose decode+augment
    runs on a ``preprocess_threads``-wide pool, with the standard
    augmentation kwargs — the layered fused fast path of
    iter_image_recordio_2.cc:663-762 (reader → parser pool → prefetcher)."""
    from .image import image as img_mod
    known = {}
    aug_keys = ("resize", "rand_crop", "rand_resize", "rand_mirror", "mean",
                "std", "brightness", "contrast", "saturation", "hue",
                "pca_noise", "rand_gray", "inter_method")
    # translate reference arg names
    if kwargs.pop("rand_mirror_prob", None):
        known["rand_mirror"] = True
    mean = None
    if any(k in kwargs for k in ("mean_r", "mean_g", "mean_b")):
        mean = np.array([kwargs.pop("mean_r", 0), kwargs.pop("mean_g", 0),
                         kwargs.pop("mean_b", 0)])
    std = None
    if any(k in kwargs for k in ("std_r", "std_g", "std_b")):
        std = np.array([kwargs.pop("std_r", 1), kwargs.pop("std_g", 1),
                        kwargs.pop("std_b", 1)])
    for k in aug_keys:
        if k in kwargs:
            known[k] = kwargs.pop(k)
    if mean is not None:
        known["mean"] = mean
    if std is not None:
        known["std"] = std
    it = img_mod.ImageIter(batch_size=batch_size, data_shape=data_shape,
                           label_width=label_width, path_imgrec=path_imgrec,
                           shuffle=shuffle, part_index=part_index,
                           num_parts=num_parts,
                           path_imgidx=kwargs.pop("path_imgidx", None),
                           preprocess_threads=preprocess_threads,
                           decode=kwargs.pop("decode", "auto"),
                           dtype=kwargs.pop("dtype", "float32"),
                           aug_list=kwargs.pop("aug_list", None),
                           ctx=kwargs.pop("ctx", None),
                           **known)
    if prefetch_buffer and int(prefetch_buffer) > 0:
        return PrefetchingIter(it, prefetch_buffer=prefetch_buffer)
    return it
