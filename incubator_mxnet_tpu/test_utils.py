"""Test harness (parity: python/mxnet/test_utils.py — the de-facto op-testing
toolkit of the reference; SURVEY §4.1).

Key pieces reproduced:
* ``default_context()`` switched by env so the same suite runs on the CPU
  mesh and on real TPU (reference: test_utils.py:53-60).
* ``assert_almost_equal`` with dtype-scaled tolerances (:470).
* ``rand_ndarray`` incl. sparse densities (:339).
* ``check_numeric_gradient`` — central finite differences vs autograd
  (:792), re-based on the tape instead of symbolic executors.
"""
from __future__ import annotations

import os

import numpy as np

from . import autograd
from .context import Context, cpu
from .ndarray import array, NDArray


def default_context():
    name = os.environ.get("MXTPU_TEST_CTX", os.environ.get("MXNET_TEST_CTX", "cpu"))
    dev = int(os.environ.get("MXTPU_TEST_DEVICE_ID", "0"))
    return Context(name, dev)


def default_dtype():
    return np.float32


_DTYPE_TOL = {
    np.dtype(np.float16): (1e-1, 1e-1),
    np.dtype("bfloat16") if hasattr(np, "bfloat16") else np.dtype(np.float16): (1e-1, 1e-1),
    np.dtype(np.float32): (1e-3, 1e-4),
    np.dtype(np.float64): (1e-5, 1e-7),
}


def _tols(a, b, rtol, atol):
    if rtol is None or atol is None:
        dt = np.promote_types(a.dtype, b.dtype) if hasattr(a, "dtype") else np.dtype(np.float32)
        r, t = _DTYPE_TOL.get(np.dtype(dt), (1e-3, 1e-4))
        return rtol or r, atol or t
    return rtol, atol


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """ref: test_utils.py:470"""
    if isinstance(a, NDArray):
        a = a.asnumpy()
    if isinstance(b, NDArray):
        b = b.asnumpy()
    a = np.asarray(a)
    b = np.asarray(b)
    rtol, atol = _tols(a, b, rtol, atol)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                               err_msg="%s vs %s mismatch" % names)


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32, ctx=None,
                 scale=1.0):
    """ref: test_utils.py:339"""
    ctx = ctx or default_context()
    if stype == "default":
        return array(np.random.uniform(-scale, scale, shape).astype(dtype), ctx=ctx)
    from .ndarray import sparse
    density = 0.3 if density is None else density
    a = np.random.uniform(-scale, scale, shape).astype(dtype)
    mask = np.random.rand(*shape) < density
    a = a * mask
    if stype == "row_sparse":
        return sparse.cast_storage(array(a, ctx=ctx), "row_sparse")
    if stype == "csr":
        return sparse.cast_storage(array(a, ctx=ctx), "csr")
    raise ValueError(stype)


def numeric_grad(f, inputs, eps=1e-2):
    """Central finite differences of scalar-valued f w.r.t. each input array."""
    grads = []
    base_inputs = [x.asnumpy().astype(np.float64) for x in inputs]
    for i, x0 in enumerate(base_inputs):
        g = np.zeros_like(x0)
        flat = x0.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = _eval_scalar(f, base_inputs)
            flat[j] = orig - eps
            fm = _eval_scalar(f, base_inputs)
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def _eval_scalar(f, np_inputs):
    nds = [array(x.astype(np.float32)) for x in np_inputs]
    out = f(*nds)
    return float(out.asnumpy().sum())


def check_numeric_gradient(f, inputs, rtol=5e-2, atol=5e-2, eps=1e-2):
    """ref: test_utils.py:792 — compare tape grads to finite differences.

    ``f``: callable over NDArrays returning one NDArray (summed to scalar).
    ``inputs``: list of NDArrays.
    """
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = f(*inputs)
        s = out.sum()
    s.backward()
    analytic = [x.grad.asnumpy() for x in inputs]
    numeric = numeric_grad(f, inputs, eps=eps)
    for i, (a, n) in enumerate(zip(analytic, numeric)):
        np.testing.assert_allclose(a, n, rtol=rtol, atol=atol,
                                   err_msg="gradient mismatch for input %d" % i)


def check_consistency(f, inputs_np, ctxs=None, rtol=None, atol=None):
    """ref: test_utils.py check_consistency — same computation across
    contexts (CPU mesh device 0/1, TPU when present) must agree."""
    from .context import num_devices
    if ctxs is None:
        ctxs = [Context("cpu", 0)]
        if num_devices("cpu") > 1:
            ctxs.append(Context("cpu", 1))
    outs = []
    for ctx in ctxs:
        nds = [array(x, ctx=ctx) for x in inputs_np]
        outs.append(f(*nds).asnumpy())
    for o in outs[1:]:
        assert_almost_equal(outs[0], o, rtol, atol)


def simple_forward(op_fn, *np_inputs, **params):
    nds = [array(np.asarray(x, np.float32)) for x in np_inputs]
    return op_fn(*nds, **params).asnumpy()
