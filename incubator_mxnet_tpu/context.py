"""Device/Context model.

Parity with python/mxnet/context.py (Context, cpu(), gpu(), current_context)
re-based on JAX devices.  ``tpu(i)`` is the accelerator context; ``gpu(i)`` is
kept as a compatibility alias that resolves to the i-th accelerator so that
reference scripts written against ``mx.gpu()`` run unchanged.

Context maps to a concrete ``jax.Device`` lazily (``jax_device()``): on a TPU
host that is a TPU chip, under the CPU test mesh it is one of the
``--xla_force_host_platform_device_count`` host devices, so multi-device
semantics (KVStore 'device', DataParallelExecutorGroup splits) are testable
without hardware — the same trick the reference uses by running
test_model_parallel on CPU contexts (SURVEY §4.1).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_devices"]


class Context:
    """Execution device. devtype: 'cpu', 'tpu' ('gpu' aliases 'tpu')."""

    _local = threading.local()
    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    devstr2type["gpu"] = 2

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            if device_type == "gpu":
                device_type = "tpu"
            if device_type not in self.devstr2type:
                raise ValueError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_typeid(self):
        return self.devstr2type[self.device_type]

    def jax_device(self):
        """Resolve to a concrete jax.Device.

        Multi-process runs: only THIS process's devices are addressable,
        so contexts index jax.local_devices() (jax.devices() is the
        global list — rank 1's "cpu(0)" must not resolve to rank 0's
        device)."""
        import jax

        multiproc = jax.process_count() > 1
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            backend = "cpu"
            try:
                devs = (jax.local_devices(backend=backend) if multiproc
                        else jax.devices(backend))
            except RuntimeError:
                devs = jax.local_devices() if multiproc else jax.devices()
            return devs[min(self.device_id, len(devs) - 1) if self.device_id >= len(devs) else self.device_id]
        # default backend: TPU if present, else host devices
        devs = jax.local_devices() if multiproc else jax.devices()
        if self.device_id >= len(devs):
            raise ValueError(
                "context %s: only %d devices available" % (self, len(devs)))
        return devs[self.device_id]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        if not hasattr(Context._local, "stack"):
            Context._local.stack = []
        Context._local.stack.append(self)
        return self

    def __exit__(self, *args):
        Context._local.stack.pop()

    @staticmethod
    def default_ctx():
        import jax

        try:
            plat = jax.default_backend()
        except Exception:
            plat = "cpu"
        return Context("tpu" if plat in ("tpu", "gpu") else "cpu", 0)

    def empty_cache(self):
        """Parity no-op: XLA owns HBM pooling (reference: GPUPooledStorageManager)."""


def current_context():
    if getattr(Context._local, "stack", None):
        return Context._local.stack[-1]
    return Context.default_ctx()


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Compatibility alias for reference scripts: resolves to the accelerator."""
    return Context("tpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_devices(device_type="tpu"):
    """Devices this process can address (multi-process: local only, to
    stay consistent with Context.jax_device resolution)."""
    import jax

    multiproc = jax.process_count() > 1
    if device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        try:
            return len(jax.local_devices(backend="cpu") if multiproc
                       else jax.devices("cpu"))
        except RuntimeError:
            return 1
    return len(jax.local_devices() if multiproc else jax.devices())


def gpu_memory_info(device_id=0):
    """(free, total) bytes on an accelerator device (ref: context.py
    gpu_memory_info → cudaMemGetInfo; here XLA's per-device allocator
    stats — the storage-manager accounting of SURVEY §2.1)."""
    for ctx_type in ("tpu", "gpu"):
        try:
            dev = Context(ctx_type, device_id).jax_device()
            break
        except Exception:
            dev = None
    if dev is None:
        raise MXNetError("no accelerator device %d" % device_id)
    stats = dev.memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return total - used, total


def memory_stats(ctx=None):
    """Full allocator statistics for a context (pool stats parity:
    src/storage/pooled_storage_manager.h — XLA's BFC allocator is the
    pool here; keys include bytes_in_use, peak_bytes_in_use,
    num_allocs, bytes_limit when the backend reports them)."""
    ctx = ctx or current_context()
    dev = ctx.jax_device()
    return dict(dev.memory_stats() or {})
