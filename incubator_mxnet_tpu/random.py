"""mx.random namespace (parity: python/mxnet/random.py)."""
from __future__ import annotations

from .random_state import seed  # noqa: F401
from .ndarray.random import (uniform, normal, gamma, exponential, poisson,  # noqa: F401
                             negative_binomial, generalized_negative_binomial,
                             multinomial, shuffle)

__all__ = ["seed", "uniform", "normal", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "multinomial",
           "shuffle"]
