"""Module: symbol + executor-group intermediate-level API.

ref: python/mxnet/module/module.py — bind/init_params/init_optimizer/
forward/backward/update over a DataParallelExecutorGroup, with KVStore
integration (update_on_kvstore semantics as in model.py _update_params*).
"""
from __future__ import annotations

import logging
import os
import warnings

import numpy as np

from .. import context as ctx_mod
from .. import engine as _engine
from .. import ndarray as nd
from .. import optimizer as opt
from .. import overlap as _overlap
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..io import DataDesc
from ..kvstore import create_kvstore as _create_kvstore
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    """ref: module.py class Module."""

    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None
        # graftduplex: Module rides the same full-duplex schedulers
        # gluon.Trainer does — bucket reduces issued mid-backward by the
        # executor's grad-ready hooks, update_on_kvstore weight pulls
        # waited at first use in the next forward
        self._scheduler = _overlap.BucketScheduler(self)
        self._pull_scheduler = _overlap.PullScheduler()

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """ref: module.py Module.load."""
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """ref: module.py save_checkpoint.  graftarmor: in-flight duplex
        handles (bucket reduces, async weight pulls, queued dist_async
        pushes) are settled FIRST so the persisted params are
        step-consistent, and ``nd.save`` underneath publishes atomically
        (tmp + rename) — a kill mid-save leaves the previous epoch's
        file intact, never a truncated one."""
        self._pull_scheduler.finish()
        drain = getattr(self._kvstore, "_drain_pushes", None)
        if drain is not None:
            drain()
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        self.logger.info('Saved checkpoint to "%s"', param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            self.logger.info('Saved optimizer state to "%s"', state_name)

    # -- properties --------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        """Inferred from the bound shapes — valid before any forward
        (ref: module.py output_shapes via the executor's inferred graph)."""
        assert self.binded
        known = {n: tuple(s) for n, s in self._data_shapes}
        for n, s in (self._label_shapes or []):
            known[n] = tuple(s)
        _, out_shapes, _ = self._symbol.infer_shape(**known)
        return list(zip(self._output_names, out_shapes))

    # -- params ------------------------------------------------------------
    def get_params(self):
        """ref: module.py get_params."""
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """ref: module.py init_params."""
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(InitDesc(name, attrs), arr)
            else:
                initializer(InitDesc(name, attrs), arr)

        attr_dict = self._symbol.attr_dict()
        if self._arg_params is None:
            self._arg_params = {}
        if self._aux_params is None:
            self._aux_params = {}
        for name in self._param_names:
            if name not in self._arg_params or \
                    self._arg_params[name] is None or force_init or \
                    (arg_params is not None and name in arg_params):
                exe0 = self._exec_group.execs[0]
                shape = exe0.arg_dict[name].shape
                arr = nd.zeros(shape)
                attrs = attr_dict.get(name, {})
                _impl(name, arr, arg_params)
                self._arg_params[name] = arr
        for name in self._aux_names:
            if name not in self._aux_params or \
                    self._aux_params[name] is None or force_init or \
                    (aux_params is not None and name in aux_params):
                exe0 = self._exec_group.execs[0]
                shape = exe0.aux_dict[name].shape
                arr = nd.zeros(shape)
                attrs = attr_dict.get(name, {})
                _impl(name, arr, aux_params)
                self._aux_params[name] = arr

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """ref: module.py set_params fast path (no re-init)."""
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "set_params call ignored.", stacklevel=2)
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: module.py bind → DataParallelExecutorGroup."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [ds if isinstance(ds, DataDesc) else DataDesc(*ds)
                             for ds in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [ls if isinstance(ls, DataDesc)
                                  else DataDesc(*ls) for ls in label_shapes]
        else:
            self._label_shapes = None

        shared_group = None
        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group, self.logger,
            self._fixed_param_names, grad_req, self._state_names)
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        """ref: module.py reshape."""
        assert self.binded
        self._data_shapes = [ds if isinstance(ds, DataDesc) else DataDesc(*ds)
                             for ds in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [ls if isinstance(ls, DataDesc)
                                  else DataDesc(*ls) for ls in label_shapes]
        self._exec_group.reshape(self._data_shapes, self._label_shapes)
        self._exec_group.set_params(self._arg_params, self._aux_params)

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """ref: module.py init_optimizer."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore_obj, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context),
            {n: self._arg_params[n] for n in self._param_names})

        batch_size = self._exec_group.batch_size
        if kvstore_obj and "dist" in kvstore_obj.type and \
                "_sync" in kvstore_obj.type:
            batch_size *= kvstore_obj.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        for i, n in enumerate(self._param_names):
            idx2name[i] = n
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s). "
                    "Is this intended?" % (optimizer.rescale_grad, rescale_grad),
                    stacklevel=2)
            if not optimizer.idx2name:
                optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore_obj:
            if self._compression_params:
                kvstore_obj.set_gradient_compression(self._compression_params)
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore_obj.init(list(range(len(self._param_names))),
                             [self._arg_params[n] for n in self._param_names])
            if update_on_kvstore:
                for idx, name in enumerate(self._param_names):
                    # sync device params to the store's (rank-0) values,
                    # ref: model.py _initialize_kvstore pull-after-init
                    kvstore_obj.pull(idx, self._exec_group.param_arrays[idx],
                                     priority=-idx)
                # device arrays may now hold rank 0's broadcast values —
                # host _arg_params are stale until the next device sync
                self._params_dirty = True
                kvstore_obj.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """ref: module.py forward (with auto-reshape for changed shapes)."""
        assert self.binded and self.params_initialized
        curr_data_shapes = tuple(i.shape for i in self._data_shapes)
        if isinstance(data_batch, list):
            assert data_batch
            new_data_shapes = tuple(d.shape for d in data_batch[0].data)
        else:
            new_data_shapes = tuple(d.shape for d in data_batch.data)
        if curr_data_shapes != new_data_shapes:
            new_dshape = [DataDesc(i.name, shape, i.dtype, i.layout)
                          for i, shape in zip(self._data_shapes,
                                              new_data_shapes)]
            if getattr(data_batch, "provide_label", None):
                new_lshape = data_batch.provide_label
            elif getattr(data_batch, "label", None) and self._label_shapes:
                new_lshape = [DataDesc(i.name, j.shape, i.dtype, i.layout)
                              for i, j in zip(self._label_shapes,
                                              data_batch.label)]
            else:
                new_lshape = None
            self.reshape(new_dshape, new_lshape)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        """ref: module.py backward."""
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply gradient updates (ref: module.py update →
        model._update_params / _update_params_on_kvstore).  Phase spans
        separate the kvstore handshake from the local updater (graftscope
        training-loop hooks).

        graftduplex: the kvstore leg is bucketed and overlapped.  On the
        local-update path the executor's grad arrays carry grad-ready
        hooks (fired by ``Executor.backward`` as it writes each grad), so
        complete buckets ship their one-buffer allreduce mid-backward
        through ``overlap.BucketScheduler`` — ``update()`` only waits,
        splits, and writes the reduced flats back into every context's
        grad arrays (bit-identical to the per-key push/pull: same
        context tree-sum, same elementwise worker reduction, and the
        write-back keeps the per-param updater contract).  On the
        update_on_kvstore path the push stays the batched per-key wire
        (the store updater's bookkeeping is per key — bit-identical
        fallback) and the weight pulls ride ``overlap.PullScheduler``:
        async per ~bucket group, waited at first touch in the next
        forward.  Serial fallbacks: compression, sparse grads,
        store-side updaters on the local path, GRAFT_OVERLAP[_PULL]=0,
        stale (user-overwritten) weights."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import tracing as _ttracing
        self._params_dirty = True
        plan = None if self._kvstore is None or self._update_on_kvstore \
            else self._module_bucket_plan()
        overlap = plan is not None and self._overlap_enabled()
        # graftwatch step journal: Module's optimizer step lands as one
        # flight-recorder event with its phase latencies (the fwd/bwd
        # phases of forward_backward record as standalone phase events)
        with _blackbox.step_journal("module",
                                    on_kvstore=self._update_on_kvstore,
                                    fused=plan is not None,
                                    overlapped=overlap):
            if self._update_on_kvstore:
                with _ttracing.phase_span("kvstore"):
                    # settle last round's in-flight weight pulls first
                    # (stale user-overwritten weights downgrade this
                    # round to the serial pull)
                    stale = self._pull_scheduler.finish()
                    keys = list(range(len(self._param_names)))
                    self._kvstore.push_many(
                        keys, [self._exec_group.grad_arrays[i]
                               for i in keys])
                    self._pull_module_weights(keys, stale)
                return
            if self._kvstore:
                with _ttracing.phase_span("kvstore"):
                    if plan is None:
                        self._scheduler.disarm()
                        keys = list(range(len(self._param_names)))
                        grads = [self._exec_group.grad_arrays[i]
                                 for i in keys]
                        # one batched multi-key push/pull: a single fused
                        # dist collective instead of one round per key
                        self._kvstore.push_many(keys, grads)
                        self._kvstore.pull_many(keys, grads)
                    else:
                        self._module_bucketed_reduce(plan)
            with _ttracing.phase_span("update"):
                for idx, name in enumerate(self._param_names):
                    for dev_i, (w, g) in enumerate(zip(
                            self._exec_group.param_arrays[idx],
                            self._exec_group.grad_arrays[idx])):
                        if g is None:
                            continue
                        self._updater(idx * len(self._context) + dev_i,
                                      g, w)
        # arm the grad-ready hooks so the NEXT backward issues each
        # bucket's reduce the moment the executor finishes its grads
        if overlap:
            self._scheduler.arm(plan)
        elif self._scheduler._armed:
            self._scheduler.disarm()

    # -- graftduplex: bucketed + overlapped kvstore leg ---------------------
    _bucket_bytes_override = None     # tests/benches force a target here
    _overlap_override = None          # tests/benches force overlap on/off
    _overlap_pull_override = None     # tests/benches force pull overlap

    def _bucket_target_bytes(self):
        if self._bucket_bytes_override is not None:
            return int(self._bucket_bytes_override)
        try:
            return int(os.environ.get(
                "GRAFT_BUCKET_BYTES",
                str(_overlap.DEFAULT_BUCKET_BYTES)))
        except ValueError:
            return _overlap.DEFAULT_BUCKET_BYTES

    def _overlap_enabled(self):
        if self._overlap_override is not None:
            return bool(self._overlap_override)
        return os.environ.get("GRAFT_OVERLAP", "1").strip().lower() \
            not in ("0", "false", "no", "off")

    def _overlap_pull_enabled(self):
        return _overlap.overlap_pull_enabled(self._overlap_pull_override)

    # overlap.BucketScheduler host protocol: carriers ARE the executor
    # grad arrays (Executor.backward fires their hooks as it writes);
    # pass ids come from the exec group's backward counter, not autograd
    _sched_autograd_hooks = False

    def _sched_entries(self, b):
        grad_arrays = self._exec_group.grad_arrays
        out = []
        for i in b.indices:
            for j, g in enumerate(grad_arrays[i]):
                if g is not None:
                    out.append(((i, j), g, g))
        return out

    def _sched_eligible(self, b):
        reqs = self._exec_group.execs[0].grad_req
        return all(reqs.get(self._param_names[i]) == "write"
                   for i in b.indices)

    def _sched_kv(self):
        return self._kvstore

    def _sched_flat(self, b):
        return self._module_bucket_flat(b)

    def _sched_pass_id(self):
        return self._exec_group.backward_passes

    def _sched_label(self, b):
        return "bucket[%s:%dp:%dB]" % (np.dtype(b.dtype).name,
                                       len(b.indices), b.nbytes)

    def _module_bucket_plan(self):
        """Bucket plan for the non-update_on_kvstore kvstore leg, or
        None for the serial per-key wire.  Buckets group by dtype (the
        update itself stays the per-param updater, so no fused-kernel or
        state-arity constraints); fallbacks: compression, a store-side
        updater (its per-key bookkeeping must see every push), sparse
        grads, unknown shapes.  Executor backward writes grads in
        arg-list order, so buckets pack in index order — there is no
        tape to feed (GRAFT_BUCKET_ORDER applies to gluon.Trainer)."""
        kv = self._kvstore
        target = self._bucket_target_bytes()
        if kv is None or target <= 0 or kv._compressor is not None \
                or kv._updater is not None:
            return None
        grad_arrays = self._exec_group.grad_arrays
        descs = []
        for i, name in enumerate(self._param_names):
            glist = grad_arrays[i]
            g0 = glist[0] if glist else None
            descs.append(None if g0 is None else
                         (str(g0.dtype), tuple(g0.shape),
                          sum(1 for g in glist if g is not None)))
        # bind_generation: a reshape swaps every executor's grad arrays,
        # so a plan (and the hooks armed on it) must rebuild even when
        # the shapes/dtypes happen to match
        sig = (target, self._exec_group.bind_generation, tuple(descs))
        cached = getattr(self, "_module_plan_cache", None)
        if cached is not None and cached[0] == sig:
            return cached[1]
        open_buckets = {}       # dtype -> (indices, nbytes)
        buckets, leftover = [], []
        for i, d in enumerate(descs):
            if d is None:
                continue
            dtype_s, shape, _n = d
            from ..ndarray.sparse import BaseSparseNDArray
            if any(isinstance(g, BaseSparseNDArray)
                   for g in grad_arrays[i] if g is not None) or not shape:
                leftover.append(i)
                continue
            dt = np.dtype(dtype_s)
            nbytes = int(np.prod(shape)) * dt.itemsize
            idxs, total = open_buckets.setdefault(dt, ([], 0))
            idxs.append(i)
            total += nbytes
            if total >= target:
                buckets.append(_overlap.Bucket(idxs, None, dt, total))
                open_buckets.pop(dt)
            else:
                open_buckets[dt] = (idxs, total)
        for dt, (idxs, total) in open_buckets.items():
            buckets.append(_overlap.Bucket(idxs, None, dt, total))
        plan = (buckets, leftover) if buckets else None
        self._module_plan_cache = (sig, plan)
        if plan is not None:
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_buckets([b.nbytes for b in buckets],
                                      len(leftover))
        return plan

    def _module_bucket_flat(self, b):
        """One bucket's concatenated local gradient — the SAME shared
        packing math as gluon's (``overlap.concat_ctx_sum``): per-exec
        flatten + committed-device-safe tree-sum in context order, so
        the bucketed reduce is bit-identical to the per-key push's
        ``KVStore._reduce``."""
        grad_arrays = self._exec_group.grad_arrays
        n_exec = len(self._exec_group.execs)
        return _overlap.concat_ctx_sum(
            [[grad_arrays[i][j] for i in b.indices]
             for j in range(n_exec)])

    def _module_bucketed_reduce(self, plan):
        """Reduce every bucket as ONE concatenated buffer (buckets the
        scheduler already issued mid-backward are only waited on), then
        split and write the reduced values back into EVERY context's
        grad arrays — the per-param updater downstream sees exactly what
        the per-key push/pull would have left there."""
        import time as _time
        buckets, leftover = plan
        kv = self._kvstore
        if leftover:
            grads = [self._exec_group.grad_arrays[i] for i in leftover]
            kv.push_many(leftover, grads)
            kv.pull_many(leftover, grads)
        overlap = self._overlap_enabled()
        issued = self._scheduler.take(plan) if overlap else {}
        serial = [b for b in buckets if id(b) not in issued]
        flats = {id(b): self._module_bucket_flat(b) for b in serial}
        if serial:
            kv.reduce_many([flats[id(b)] for b in serial])
        reduced, exposed_s, inflight_s = {}, 0.0, 0.0
        for b in buckets:
            entry = issued.get(id(b))
            if entry is None:
                reduced[id(b)] = flats[id(b)]
                continue
            flat, handle = entry
            t0 = _time.perf_counter()
            handle.wait()
            t1 = _time.perf_counter()
            exposed_s += t1 - t0
            inflight_s += t1 - handle.issued_at
            reduced[id(b)] = flat
        if overlap:
            if issued:
                kv.heartbeat()      # same wait-side heartbeat contract
                #                     as gluon's overlapped step
            from ..telemetry import metrics as _tmetrics
            _tmetrics.trainer_overlap(len(issued), len(serial),
                                      exposed_s, inflight_s)
        grad_arrays = self._exec_group.grad_arrays
        for b in buckets:
            flat = reduced[id(b)]
            shapes = [tuple(grad_arrays[i][0].shape) for i in b.indices]
            pieces = _engine.split_flat(flat._read(), shapes)
            for pos, i in enumerate(b.indices):
                for g in grad_arrays[i]:
                    if g is not None:
                        g._write(_engine.colocate(pieces[pos], g._read()))

    def _pull_module_weights(self, keys, stale=0):
        """update_on_kvstore weight broadcast: async per ~bucket-size
        group with first-touch waits (``overlap.PullScheduler``) when
        the pull side is on; the synchronous batched ``pull_many``
        otherwise.  ``stale`` > 0 — a weight the user overwrote while
        its pull was in flight — forces one serial round
        (abandon-and-fallback); sparse param arrays always pull
        serially (exactly gluon's rails, via the shared
        ``overlap.pull_round``)."""
        from ..ndarray.sparse import BaseSparseNDArray
        param_arrays = self._exec_group.param_arrays
        overlap = self._overlap_pull_enabled() and not stale \
            and not any(isinstance(w, BaseSparseNDArray)
                        for i in keys for w in param_arrays[i])
        sizes = [int(np.prod(param_arrays[i][0].shape))
                 * np.dtype(param_arrays[i][0].dtype).itemsize
                 for i in keys]
        _overlap.pull_round(
            self._pull_scheduler, self._kvstore, keys,
            [param_arrays[i] for i in keys], sizes,
            self._bucket_target_bytes(), overlap)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        """ref: module.py _sync_params_from_devices."""
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        """ref: module.py save_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        """ref: module.py load_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        for exe in self._exec_group.execs:
            mon.install(exe)
