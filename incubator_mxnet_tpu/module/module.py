"""Module: symbol + executor-group intermediate-level API.

ref: python/mxnet/module/module.py — bind/init_params/init_optimizer/
forward/backward/update over a DataParallelExecutorGroup, with KVStore
integration (update_on_kvstore semantics as in model.py _update_params*).
"""
from __future__ import annotations

import logging
import warnings

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from ..base import MXNetError
from ..initializer import Uniform, InitDesc
from ..io import DataDesc
from ..kvstore import create_kvstore as _create_kvstore
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    """ref: module.py class Module."""

    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = context
        if work_load_list is None:
            work_load_list = [1] * len(self._context)
        self._work_load_list = work_load_list

        self._symbol = symbol
        data_names = list(data_names) if data_names is not None else []
        label_names = list(label_names) if label_names is not None else []

        arg_names = symbol.list_arguments()
        input_names = data_names + label_names
        self._param_names = [x for x in arg_names if x not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._data_names = data_names
        self._label_names = label_names
        self._state_names = list(state_names or [])
        self._output_names = symbol.list_outputs()

        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._compression_params = compression_params
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._preload_opt_states = None
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """ref: module.py Module.load."""
        from ..model import load_checkpoint
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """ref: module.py save_checkpoint."""
        self._symbol.save("%s-symbol.json" % prefix)
        param_name = "%s-%04d.params" % (prefix, epoch)
        self.save_params(param_name)
        self.logger.info('Saved checkpoint to "%s"', param_name)
        if save_optimizer_states:
            state_name = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(state_name)
            self.logger.info('Saved optimizer state to "%s"', state_name)

    # -- properties --------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        """Inferred from the bound shapes — valid before any forward
        (ref: module.py output_shapes via the executor's inferred graph)."""
        assert self.binded
        known = {n: tuple(s) for n, s in self._data_shapes}
        for n, s in (self._label_shapes or []):
            known[n] = tuple(s)
        _, out_shapes, _ = self._symbol.infer_shape(**known)
        return list(zip(self._output_names, out_shapes))

    # -- params ------------------------------------------------------------
    def get_params(self):
        """ref: module.py get_params."""
        assert self.binded and self.params_initialized
        if self._params_dirty:
            self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """ref: module.py init_params."""
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "init_params call ignored.", stacklevel=2)
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)

        def _impl(name, arr, cache):
            if cache is not None:
                if name in cache:
                    cache_arr = cache[name]
                    if cache_arr is not arr:
                        cache_arr.copyto(arr)
                else:
                    if not allow_missing:
                        raise RuntimeError("%s is not presented" % name)
                    if initializer is not None:
                        initializer(InitDesc(name, attrs), arr)
            else:
                initializer(InitDesc(name, attrs), arr)

        attr_dict = self._symbol.attr_dict()
        if self._arg_params is None:
            self._arg_params = {}
        if self._aux_params is None:
            self._aux_params = {}
        for name in self._param_names:
            if name not in self._arg_params or \
                    self._arg_params[name] is None or force_init or \
                    (arg_params is not None and name in arg_params):
                exe0 = self._exec_group.execs[0]
                shape = exe0.arg_dict[name].shape
                arr = nd.zeros(shape)
                attrs = attr_dict.get(name, {})
                _impl(name, arr, arg_params)
                self._arg_params[name] = arr
        for name in self._aux_names:
            if name not in self._aux_params or \
                    self._aux_params[name] is None or force_init or \
                    (aux_params is not None and name in aux_params):
                exe0 = self._exec_group.execs[0]
                shape = exe0.aux_dict[name].shape
                arr = nd.zeros(shape)
                attrs = attr_dict.get(name, {})
                _impl(name, arr, aux_params)
                self._aux_params[name] = arr

        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        """ref: module.py set_params fast path (no re-init)."""
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("Parameters already initialized and force_init=False. "
                          "set_params call ignored.", stacklevel=2)
            return
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty = True
        self.params_initialized = True

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """ref: module.py bind → DataParallelExecutorGroup."""
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [ds if isinstance(ds, DataDesc) else DataDesc(*ds)
                             for ds in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [ls if isinstance(ls, DataDesc)
                                  else DataDesc(*ls) for ls in label_shapes]
        else:
            self._label_shapes = None

        shared_group = None
        if shared_module is not None:
            assert isinstance(shared_module, Module) and \
                shared_module.binded and shared_module.params_initialized
            shared_group = shared_module._exec_group

        self._exec_group = DataParallelExecutorGroup(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group, self.logger,
            self._fixed_param_names, grad_req, self._state_names)
        if shared_module is not None:
            self.params_initialized = True
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _reset_bind(self):
        self.binded = False
        self._exec_group = None
        self._data_shapes = None
        self._label_shapes = None

    def reshape(self, data_shapes, label_shapes=None):
        """ref: module.py reshape."""
        assert self.binded
        self._data_shapes = [ds if isinstance(ds, DataDesc) else DataDesc(*ds)
                             for ds in data_shapes]
        if label_shapes is not None:
            self._label_shapes = [ls if isinstance(ls, DataDesc)
                                  else DataDesc(*ls) for ls in label_shapes]
        self._exec_group.reshape(self._data_shapes, self._label_shapes)
        self._exec_group.set_params(self._arg_params, self._aux_params)

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """ref: module.py init_optimizer."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        (kvstore_obj, update_on_kvstore) = _create_kvstore(
            kvstore, len(self._context),
            {n: self._arg_params[n] for n in self._param_names})

        batch_size = self._exec_group.batch_size
        if kvstore_obj and "dist" in kvstore_obj.type and \
                "_sync" in kvstore_obj.type:
            batch_size *= kvstore_obj.num_workers
        rescale_grad = 1.0 / batch_size

        idx2name = {}
        for i, n in enumerate(self._param_names):
            idx2name[i] = n
        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self.symbol,
                                   param_idx2name=idx2name, **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)
            if optimizer.rescale_grad != rescale_grad:
                warnings.warn(
                    "Optimizer created manually outside Module but rescale_grad "
                    "is not normalized to 1.0/batch_size/num_workers (%s vs. %s). "
                    "Is this intended?" % (optimizer.rescale_grad, rescale_grad),
                    stacklevel=2)
            if not optimizer.idx2name:
                optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore_obj
        self._update_on_kvstore = update_on_kvstore
        self._updater = None

        if kvstore_obj:
            if self._compression_params:
                kvstore_obj.set_gradient_compression(self._compression_params)
            # one batched init: on dist stores this is a single rank-0
            # broadcast collective for all params, not one per key
            kvstore_obj.init(list(range(len(self._param_names))),
                             [self._arg_params[n] for n in self._param_names])
            if update_on_kvstore:
                for idx, name in enumerate(self._param_names):
                    # sync device params to the store's (rank-0) values,
                    # ref: model.py _initialize_kvstore pull-after-init
                    kvstore_obj.pull(idx, self._exec_group.param_arrays[idx],
                                     priority=-idx)
                # device arrays may now hold rank 0's broadcast values —
                # host _arg_params are stale until the next device sync
                self._params_dirty = True
                kvstore_obj.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)

        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """ref: module.py forward (with auto-reshape for changed shapes)."""
        assert self.binded and self.params_initialized
        curr_data_shapes = tuple(i.shape for i in self._data_shapes)
        if isinstance(data_batch, list):
            assert data_batch
            new_data_shapes = tuple(d.shape for d in data_batch[0].data)
        else:
            new_data_shapes = tuple(d.shape for d in data_batch.data)
        if curr_data_shapes != new_data_shapes:
            new_dshape = [DataDesc(i.name, shape, i.dtype, i.layout)
                          for i, shape in zip(self._data_shapes,
                                              new_data_shapes)]
            if getattr(data_batch, "provide_label", None):
                new_lshape = data_batch.provide_label
            elif getattr(data_batch, "label", None) and self._label_shapes:
                new_lshape = [DataDesc(i.name, j.shape, i.dtype, i.layout)
                              for i, j in zip(self._label_shapes,
                                              data_batch.label)]
            else:
                new_lshape = None
            self.reshape(new_dshape, new_lshape)
        self._exec_group.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        """ref: module.py backward."""
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply gradient updates (ref: module.py update →
        model._update_params / _update_params_on_kvstore).  Phase spans
        separate the kvstore handshake from the local updater (graftscope
        training-loop hooks)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        from ..telemetry import blackbox as _blackbox
        from ..telemetry import tracing as _ttracing
        self._params_dirty = True
        # graftwatch step journal: Module's optimizer step lands as one
        # flight-recorder event with its phase latencies (the fwd/bwd
        # phases of forward_backward record as standalone phase events)
        with _blackbox.step_journal("module",
                                    on_kvstore=self._update_on_kvstore):
            if self._update_on_kvstore:
                with _ttracing.phase_span("kvstore"):
                    for idx, name in enumerate(self._param_names):
                        grads = self._exec_group.grad_arrays[idx]
                        self._kvstore.push(idx, grads, priority=-idx)
                        self._kvstore.pull(
                            idx, self._exec_group.param_arrays[idx],
                            priority=-idx)
                return
            if self._kvstore:
                with _ttracing.phase_span("kvstore"):
                    for idx, name in enumerate(self._param_names):
                        grads = self._exec_group.grad_arrays[idx]
                        self._kvstore.push(idx, grads, priority=-idx)
                        self._kvstore.pull(idx, grads, priority=-idx)
            with _ttracing.phase_span("update"):
                for idx, name in enumerate(self._param_names):
                    for dev_i, (w, g) in enumerate(zip(
                            self._exec_group.param_arrays[idx],
                            self._exec_group.grad_arrays[idx])):
                        if g is None:
                            continue
                        self._updater(idx * len(self._context) + dev_i,
                                      g, w)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and self.inputs_need_grad
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def _sync_params_from_devices(self):
        """ref: module.py _sync_params_from_devices."""
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    def save_optimizer_states(self, fname):
        """ref: module.py save_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        """ref: module.py load_optimizer_states."""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        for exe in self._exec_group.execs:
            mon.install(exe)
