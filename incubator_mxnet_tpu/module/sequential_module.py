"""SequentialModule: chain modules so each consumes the previous one's
outputs (ref: python/mxnet/module/sequential_module.py).
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """A container chaining sub-modules in order; data shapes propagate
    through (ref: sequential_module.py class SequentialModule).  Use
    ``add(mod, take_labels=True)`` on the module that consumes the loss
    labels (typically the last)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, **kwargs):
        """Append a sub-module (ref: sequential_module.py add)."""
        self._modules.append(module)
        for key in kwargs:
            if key not in (self.META_TAKE_LABELS, self.META_AUTO_WIRING):
                raise ValueError("unknown meta %r" % key)
        self._metas.append(dict(kwargs))
        self.binded = False
        self.params_initialized = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Chain-bind: module i+1's data shapes are module i's output
        shapes (ref: sequential_module.py bind)."""
        if self.binded and not force_rebind:
            return
        if not self._modules:
            raise MXNetError("SequentialModule has no sub-modules")
        self._label_shapes = label_shapes
        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            if i > 0 and meta.get(self.META_AUTO_WIRING, False):
                # rename the previous outputs onto this module's inputs
                my_data_shapes = [(name, tuple(shape)) for name, (_, shape)
                                  in zip(module.data_names, my_data_shapes)]
            else:
                my_data_shapes = [(n, tuple(s)) for n, s in my_data_shapes]
            meta_labels = meta.get(self.META_TAKE_LABELS, False)
            module.bind(
                data_shapes=my_data_shapes,
                label_shapes=label_shapes if meta_labels else None,
                for_training=for_training,
                inputs_need_grad=inputs_need_grad or i > 0,
                force_rebind=force_rebind)
            if meta_labels:
                anybody_ever_needs_label = True
            my_data_shapes = module.output_shapes
        if not anybody_ever_needs_label:
            self._label_shapes = None
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer,
                               arg_params=arg_params, aux_params=aux_params,
                               allow_missing=allow_missing or
                               arg_params is not None,
                               force_init=force_init)
        self.params_initialized = True

    def get_params(self):
        arg, aux = {}, {}
        for module in self._modules:
            a, x = module.get_params()
            arg.update(a)
            aux.update(x)
        return arg, aux

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        for module in self._modules:
            module.set_params(arg_params, aux_params, allow_missing=True,
                              force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        """Feed through the chain (ref: sequential_module.py forward)."""
        from ..io import DataBatch
        assert self.binded
        batch = data_batch
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            module.forward(batch, is_train=is_train)
            if i + 1 == len(self._modules):
                break
            out = module.get_outputs()
            label = data_batch.label if \
                self._metas[i + 1].get(self.META_TAKE_LABELS, False) else None
            batch = DataBatch(data=out, label=label)

    def backward(self, out_grads=None):
        """Back through the chain in reverse (ref: sequential_module.py)."""
        assert self.binded
        grads = out_grads
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=grads)
            if i == 0:
                break
            grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
