"""DataParallelExecutorGroup (ref: python/mxnet/module/executor_group.py).

Splits each batch across contexts, one Executor per context, gradient
aggregation hooks for the update path (ref: executor_group.py:129,267,422).
On a TPU mesh the fused path is parallel.DataParallelTrainer; this class
keeps the Module API's multi-context contract (slices over logical
devices — useful on the virtual CPU mesh and for ported scripts).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as nd
from ..io import DataDesc


def _split_input_slice(batch_size, work_load_list):
    """ref: executor_group.py _split_input_slice / decide_slices."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


class DataParallelExecutorGroup(object):
    """ref: executor_group.py class DataParallelExecutorGroup."""

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad, shared_group=None,
                 logger=None, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.grad_req = grad_req
        self.shared_group = shared_group

        self.batch_size = None
        self.slices = None
        self.execs = []
        self.data_shapes = None
        self.label_shapes = None
        self.data_names = None
        self.label_names = None
        self.output_layouts = None
        self.num_outputs = None
        self.backward_passes = 0    # graftduplex: the Module bucket
        #                             scheduler's pass id (the role
        #                             autograd.backward_pass_id plays
        #                             for gluon) — bumped per backward
        self.bind_generation = 0    # bumped per (re)bind: a reshape
        #                             swaps every executor's arrays, so
        #                             plans/hooks keyed on the old ones
        #                             must rebuild

        self.bind_exec(data_shapes, label_shapes, shared_group)

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        """Bind one executor per context (ref: executor_group.py bind_exec)."""
        self.bind_generation += 1
        self.batch_size = data_shapes[0][1][0]
        self.slices = _split_input_slice(self.batch_size, self.workload)
        self.data_shapes = [DataDesc(*ds) if not isinstance(ds, DataDesc)
                            else ds for ds in data_shapes]
        self.data_names = [ds.name for ds in self.data_shapes]
        if label_shapes is not None:
            self.label_shapes = [DataDesc(*ls) if not isinstance(ls, DataDesc)
                                 else ls for ls in label_shapes]
            self.label_names = [ls.name for ls in self.label_shapes]
        else:
            self.label_shapes = None
            self.label_names = []

        self.execs = []
        for i, ctx in enumerate(self.contexts):
            n_i = self.slices[i].stop - self.slices[i].start
            shapes = {}
            for ds in self.data_shapes:
                shapes[ds.name] = (n_i,) + tuple(ds.shape[1:])
            if self.label_shapes:
                for ls in self.label_shapes:
                    shapes[ls.name] = (n_i,) + tuple(ls.shape[1:])
            grad_req = {}
            for name in self.arg_names:
                if not self.for_training or name in self.fixed_param_names or \
                        name in shapes:  # data/label get no grads by default
                    if name in shapes and self.inputs_need_grad and \
                            name in self.data_names:
                        grad_req[name] = "write"
                    else:
                        grad_req[name] = "null"
                else:
                    grad_req[name] = self.grad_req if isinstance(self.grad_req, str) \
                        else self.grad_req.get(name, "write")
            shared_exec = shared_group.execs[i] if shared_group else None
            exe = self.symbol.simple_bind(ctx=ctx, grad_req=grad_req,
                                          shared_exec=shared_exec, **shapes)
            self.execs.append(exe)
        self.num_outputs = len(self.symbol.list_outputs())

    def reshape(self, data_shapes, label_shapes):
        """ref: executor_group.py reshape."""
        self.bind_exec(data_shapes, label_shapes, self.shared_group,
                       reshape=True)

    def set_params(self, arg_params, aux_params, allow_extra=False):
        """ref: executor_group.py set_params."""
        for exe in self.execs:
            exe.copy_params_from(arg_params, aux_params,
                                 allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Average params over devices into the given dicts
        (ref: executor_group.py get_params)."""
        for name in self.param_names:
            arrs = [exe.arg_dict[name] for exe in self.execs]
            acc = arrs[0].asnumpy().astype(np.float64)
            for a in arrs[1:]:
                acc += a.asnumpy()
            arg_params[name] = nd.array((acc / len(arrs)).astype(
                arrs[0].dtype))
        for name in self.aux_names:
            arrs = [exe.aux_dict[name] for exe in self.execs]
            acc = arrs[0].asnumpy().astype(np.float64)
            for a in arrs[1:]:
                acc += a.asnumpy()
            aux_params[name] = nd.array((acc / len(arrs)).astype(
                arrs[0].dtype))

    def forward(self, data_batch, is_train=None):
        """Slice the batch per context and run (ref: executor_group.py:422)."""
        if is_train is None:
            is_train = self.for_training
        for i, exe in enumerate(self.execs):
            sl = self.slices[i]
            feed = {}
            for name, arr in zip(self.data_names, data_batch.data):
                feed[name] = arr[sl.start:sl.stop]
            if self.label_names and data_batch.label:
                for name, arr in zip(self.label_names, data_batch.label):
                    feed[name] = arr[sl.start:sl.stop]
            exe.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        """ref: executor_group.py backward."""
        assert self.for_training, "re-bind with for_training=True to run backward"
        self.backward_passes += 1
        for i, exe in enumerate(self.execs):
            og = None
            if out_grads is not None:
                sl = self.slices[i]
                og = [g[sl.start:sl.stop] for g in out_grads]
            exe.backward(out_grads=og)

    def get_outputs(self, merge_multi_context=True):
        """ref: executor_group.py get_outputs."""
        outputs = [[exe.outputs[i] for exe in self.execs]
                   for i in range(self.num_outputs)]
        if merge_multi_context:
            return [nd.ndarray.concatenate(parts, axis=0) if len(parts) > 1
                    else parts[0] for parts in outputs]
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        """ref: executor_group.py get_input_grads."""
        assert self.inputs_need_grad
        grads = [[exe.grad_dict[name] for exe in self.execs]
                 for name in self.data_names]
        if merge_multi_context:
            return [nd.ndarray.concatenate(parts, axis=0) if len(parts) > 1
                    else parts[0] for parts in grads]
        return grads

    def update_metric(self, eval_metric, labels):
        """ref: executor_group.py update_metric."""
        for i, exe in enumerate(self.execs):
            sl = self.slices[i]
            labels_slice = [label[sl.start:sl.stop] for label in labels]
            eval_metric.update(labels_slice, exe.outputs)

    @property
    def grad_arrays(self):
        """grad arrays grouped per param then per device."""
        return [[exe.grad_dict.get(name) for exe in self.execs]
                for name in self.param_names]

    @property
    def param_arrays(self):
        return [[exe.arg_dict[name] for exe in self.execs]
                for name in self.param_names]

    @property
    def aux_arrays(self):
        return [[exe.aux_dict[name] for exe in self.execs]
                for name in self.aux_names]

    def set_monitor_callback(self, callback):
        for exe in self.execs:
            exe.set_monitor_callback(callback)
