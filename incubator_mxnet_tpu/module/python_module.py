"""PythonModule / PythonLossModule: modules computed in plain Python
(ref: python/mxnet/module/python_module.py).

These let arbitrary host code (metrics-free losses, beam search, glue
layers) participate in a Module pipeline — typically inside
SequentialModule — without owning parameters or executors.
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Parameter-less module whose forward is written in Python
    (ref: python_module.py class PythonModule)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None
        self.params_initialized = False

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def set_params(self, arg_params, aux_params=None, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        pass

    def update(self):
        pass

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self._data_shapes = [(n, tuple(s)) for n, s in data_shapes]
        self._label_shapes = ([(n, tuple(s)) for n, s in label_shapes]
                              if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()
        self.binded = True
        self.for_training = for_training

    def _compute_output_shapes(self):
        """Default: one output mirroring the first data shape; override
        for anything else (ref: python_module.py _compute_output_shapes)."""
        return [(self._output_names[0], tuple(self._data_shapes[0][1]))]

    def update_metric(self, eval_metric, labels):
        pass

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """A Python-computed loss head: forward passes scores through,
    backward supplies a Python-computed gradient
    (ref: python_module.py class PythonLossModule)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "PythonLossModule is a loss head"
        assert self.for_training
        if self._grad_func is not None:
            g = self._grad_func(self._scores, self._labels)
            if not isinstance(g, NDArray):
                g = nd.array(np.asarray(g))
            self._scores_grad = g
        else:
            # default: d/ds of softmax CE with integer labels
            s = self._scores.asnumpy()
            e = np.exp(s - s.max(axis=1, keepdims=True))
            p = e / e.sum(axis=1, keepdims=True)
            y = self._labels.asnumpy().astype(np.int64)
            p[np.arange(len(y)), y] -= 1.0
            self._scores_grad = nd.array(p)

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
