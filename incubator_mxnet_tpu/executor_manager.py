"""Executor manager shim (ref: python/mxnet/executor_manager.py).

The reference's DataParallelExecutorManager predates the Module API and
managed per-device executors + slices by hand; Module's ExecutorGroup
(module/executor_group.py here) is its successor and owns the real
logic.  This module keeps the public helpers old scripts import.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import DataParallelExecutorGroup

__all__ = ["_split_input_slice", "_check_arguments",
           "DataParallelExecutorManager"]


def _split_input_slice(batch_size, work_load_list):
    """Slice a batch across devices by workload (ref:
    executor_manager.py _split_input_slice)."""
    total = sum(work_load_list)
    if total <= 0:
        raise ValueError("Invalid work_load_list")
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        if end > batch_size or end <= start:
            raise ValueError("Too many slices. Some splits are empty.")
        slices.append(slice(start, end))
        start = end
    return slices


def _check_arguments(symbol):
    """Reject duplicate argument/aux names (ref: executor_manager.py
    _check_arguments)."""
    arg_names = symbol.list_arguments()
    if len(arg_names) != len(set(arg_names)):
        raise MXNetError("Find duplicated argument name: %s" % arg_names)
    aux_names = symbol.list_auxiliary_states()
    if len(aux_names) != len(set(aux_names)):
        raise MXNetError("Find duplicated auxiliary name: %s" % aux_names)


class DataParallelExecutorManager(object):
    """Legacy facade over DataParallelExecutorGroup
    (ref: executor_manager.py class DataParallelExecutorManager)."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=logging, sym_gen=None):
        _check_arguments(symbol)
        self.symbol = symbol
        self.ctx = ctx if isinstance(ctx, list) else [ctx]
        self.logger = logger
        data_shapes = list(train_data.provide_data)
        label_shapes = list(train_data.provide_label or [])
        input_names = ([d[0] for d in data_shapes]
                       + [l[0] for l in label_shapes])
        self._param_names = param_names or [
            n for n in symbol.list_arguments() if n not in input_names]
        self.execgrp = DataParallelExecutorGroup(
            symbol, self.ctx, work_load_list, data_shapes, label_shapes,
            self._param_names, for_training=True, inputs_need_grad=False,
            logger=logger)

    @property
    def param_names(self):
        return self._param_names

    @property
    def aux_names(self):
        return self.symbol.list_auxiliary_states()

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def load_data_batch(self, data_batch):
        self._batch = data_batch

    def forward(self, is_train=False):
        self.execgrp.forward(self._batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)
