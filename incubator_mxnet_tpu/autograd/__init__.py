"""Autograd: tape-based reverse-mode differentiation over eager ops.

TPU-native rebirth of src/imperative/imperative.cc (+ python/mxnet/autograd.py):

* ``record()/pause()/train_mode()/predict_mode()`` scopes == the reference's
  thread-local ``is_recording_/is_train_`` flags (imperative.cc:25-29).
* Each recorded eager op stores the ``jax.vjp`` closure of its own jitted
  fcompute — the tape IS the gradient graph, so there is no separate
  ``pass::Gradient`` construction step (imperative.cc:433): XLA already owns
  the per-op backward kernels.
* ``backward()`` walks the tape in reverse accumulating cotangents
  (RunGraph over the backward graph, imperative.cc:268).
* ``grad()`` with ``create_graph=True`` re-records each vjp application,
  giving higher-order gradients (parity with autograd.py:270).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "mark_variables",
           "backward", "grad", "get_symbol", "Function",
           "backward_pass_id", "register_hook_source",
           "unregister_hook_source"]

_state = threading.local()

# graftlap: consumers that installed _grad_ready_hook attrs register here
# so a hook-less process never pays the per-backward finalization prescan
# (an O(tape fan-in) getattr walk).  A WeakSet: a Trainer dropped without
# disarming vanishes from the set on GC, re-gating the scan by itself.
import weakref as _weakref
_hook_sources = _weakref.WeakSet()


def register_hook_source(source):
    """Declare that ``source`` has grad-ready hooks installed somewhere
    (gluon's _BucketScheduler).  Only the set's non-emptiness matters."""
    _hook_sources.add(source)


def unregister_hook_source(source):
    _hook_sources.discard(source)


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.backward_passes = 0
    return _state


def backward_pass_id():
    """Monotonic id of the calling thread's latest backward pass.

    graftlap consumers (the Trainer's bucket scheduler) use it to tell
    gradients of the CURRENT pass from leftovers of an earlier one: a
    grad-ready hook firing under a new pass id means every in-flight
    reduce issued during the previous pass is stale and must be
    discarded before scheduling restarts."""
    return _st().backward_passes


def is_recording():
    return _st().recording


def head_seed(value):
    """THE backward seeding rule for a head with no explicit head_grad:
    ones of the head's shape/dtype (``d(sum)/d`` semantics, parity with
    the reference's ``backward()``).  Single source of truth shared by
    the tape walk (:func:`_run_backward`) and the compiled whole-step
    vjp (``gluon/step_compile.py``), so ``loss.backward()`` and the
    fused fwd+bwd program are seeded identically by construction."""
    return jnp.ones_like(value)


def is_training():
    return _st().training


def set_recording(is_recording):  # noqa: A002 - parity signature
    s = _st()
    prev = s.recording
    s.recording = bool(is_recording)
    return prev


def set_training(train_mode):
    s = _st()
    prev = s.training
    s.training = bool(train_mode)
    return prev


@contextmanager
def _scope(recording=None, training=None):
    s = _st()
    prev_r, prev_t = s.recording, s.training
    if recording is not None:
        s.recording = recording
    if training is not None:
        s.training = training
    try:
        yield
    finally:
        s.recording, s.training = prev_r, prev_t


@contextmanager
def record(train_mode=True):
    """ref: autograd.py:93 record scope.

    graftlens: the time spent inside a record scope is the training
    loop's *forward* build — it feeds the per-step ``forward`` component
    (Module's ``fwd`` phase span covers the symbolic path; overlapping
    reports union in the lens sweep, so double instrumentation cannot
    double-count)."""
    import time as _time
    from ..telemetry import lens as _lens
    t0 = _time.perf_counter() if _lens.enabled() else None
    with _scope(recording=True, training=train_mode):
        try:
            yield
        finally:
            if t0 is not None:
                _lens.interval("forward", t0, _time.perf_counter())


def pause(train_mode=False):
    return _scope(recording=False, training=train_mode)


def train_mode():
    return _scope(training=True)


def predict_mode():
    return _scope(training=False)


class TapeNode:
    __slots__ = ("op", "inputs", "outputs", "vjp", "fn", "used")

    def __init__(self, op, inputs, outputs, vjp, fn=None):
        self.op = op
        self.inputs = inputs      # list[NDArray] (strong refs keep tape valid)
        self.outputs = outputs    # list[NDArray]
        self.vjp = vjp
        self.fn = fn              # pure fn of inputs (higher-order replay)
        self.used = False


def _record(op, inputs, outputs, vjp_fn, fn=None):
    """Called by ndarray.invoke under recording (RecordOp, imperative.cc:182)."""
    s = _st()
    node = TapeNode(op, inputs, outputs, vjp_fn, fn)
    for i, o in enumerate(outputs):
        o._tape_ref = (node, i)
    s.tape.append(node)


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: imperative.cc:112 MarkVariables — attach grad buffers."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _run_backward(heads, head_grads, retain_graph, train_mode, variables=None,
                  create_graph=False):
    """Reverse pass over the tape (RunGraph over the gradient graph,
    imperative.cc:268).

    Plain mode accumulates raw device values.  With ``create_graph`` the
    pass runs *as recorded eager ops*: each vjp application becomes a new
    tape node whose inputs are the primal inputs plus the incoming
    cotangents (so second derivatives see both dependencies), and
    cotangent accumulation goes through the recorded add op — the
    returned gradients are ordinary tape-connected NDArrays.

    graftlap: arrays carrying a ``_grad_ready_hook`` attribute have their
    gradient delivered *mid-walk*, the moment it is final — an input's
    gradient can only change while nodes listing it as an input are
    processed, so once the reverse walk passes the input's earliest tape
    position the accumulated cotangent is the finished gradient.  The
    hook fires right after delivery, which is what lets the Trainer's
    bucket scheduler issue a bucket's allreduce while backward is still
    producing earlier-layer gradients.  Hooks are suppressed whenever
    the pass is not a plain full backward (``create_graph``, an explicit
    ``variables`` list, or ``retain_graph`` — where a later pass may
    legally re-write the delivered grads): consumers fall back to their
    serial path.
    """
    # any bulk-deferred segment must land its tape node before the walk
    # (a recorded segment only becomes a node at flush)
    from .. import engine as _engine
    _engine.flush(cause="autograd")

    s = _st()
    s.backward_passes += 1
    tape = list(s.tape)
    from ..telemetry import metrics as _tmetrics
    _tmetrics.autograd_backward(len(tape))
    grads: dict[int, object] = {}

    from ..ndarray.ndarray import NDArray, invoke
    from ..ops.registry import get_op

    def _seed(h, hg):
        v = head_seed(h._read()) if hg is None else hg._read()
        return NDArray(v) if create_graph else v

    for i, h in enumerate(heads):
        grads[id(h)] = _seed(h, None if head_grads is None
                             else head_grads[i])

    def _zero_ct(o):
        z = jnp.zeros_like(o._read())
        return NDArray(z) if create_graph else z

    def _accum(key, g):
        if key not in grads:
            grads[key] = g
        elif create_graph:
            grads[key] = invoke(get_op("elemwise_add"), [grads[key], g], {})
        else:
            grads[key] = grads[key] + g

    # graftlap finalization schedule: for every hooked grad-receiving
    # input, the tape index of its EARLIEST appearance — once the reverse
    # walk passes that index the accumulated cotangent is final.  Built
    # only for the plain full-backward shape (see docstring); hooked
    # arrays are delivered early, everything else keeps the end-of-walk
    # delivery below, so semantics are unchanged for non-participants.
    fire_hooks = variables is None and not create_graph \
        and not retain_graph and bool(_hook_sources)
    final_at = {}               # tape index -> [NDArray, ...]
    if fire_hooks:
        seen = set()
        for k, node in enumerate(tape):
            for idx, inp in enumerate(node.inputs):
                if idx in node.op.nograd_inputs or id(inp) in seen:
                    continue
                if getattr(inp, "_grad_ready_hook", None) is not None \
                        and inp._grad is not None \
                        and inp._grad_req != "null":
                    seen.add(id(inp))
                    final_at.setdefault(k, []).append(inp)
                    # graftduplex tape-order feedback: the earliest tape
                    # position is where this input's gradient FINALIZES
                    # on the reverse walk (higher = earlier).  The
                    # Trainer's bucket packer sorts on it
                    # (GRAFT_BUCKET_ORDER=tape) so first-to-finalize
                    # params share the first buckets and their reduces
                    # hit the wire earliest.
                    inp._tape_pos = k

    for k in range(len(tape) - 1, -1, -1):
        node = tape[k]
        if any(id(o) in grads for o in node.outputs):
            if node.used and not retain_graph:
                raise RuntimeError(
                    "graph already backpropagated; use retain_graph=True "
                    "(parity: mxnet 'hit a node twice' check)")
            out_cts = tuple(grads.get(id(o)) if id(o) in grads
                            else _zero_ct(o) for o in node.outputs)
            if create_graph:
                in_cts = _recorded_vjp(node, out_cts)
            else:
                ct = out_cts[0] if len(out_cts) == 1 else out_cts
                in_cts = node.vjp(ct)
            for idx, (inp, g) in enumerate(zip(node.inputs, in_cts)):
                if idx in node.op.nograd_inputs or g is None:
                    continue
                _accum(id(inp), g)
            if not retain_graph:
                node.used = True
        for arr in final_at.pop(k, ()):
            # final for this pass: deliver now and tell the scheduler —
            # last-layer grads (high tape indices) fire first, giving the
            # reverse-topological bucket order that lets their reduces
            # overlap the rest of the walk
            if id(arr) in grads:
                _deliver(arr, grads, create_graph)
                _fire_ready_hook(arr)

    results = None
    if variables is not None:
        results = []
        for v in variables:
            g = grads.get(id(v))
            if g is None:
                g = _zero_ct(v)
            results.append(g)
    for k, node in enumerate(tape):
        for arr in node.inputs:
            if _deliver(arr, grads, create_graph) and variables is None \
                    and not create_graph:
                # graftduplex tape-order feedback, the hook-less twin of
                # the prescan stamp above: this forward-order sweep hits
                # each delivered input at its EARLIEST tape position, so
                # the very FIRST backward hands the Trainer's bucket
                # packer its ordering — the first bucket plan is already
                # tape-ordered and never rebuilds (a rebuild would
                # abandon the transition step's in-flight reduces)
                arr._tape_pos = k
    for h in heads:
        _deliver(h, grads, create_graph)
    if not retain_graph and not create_graph:
        s.tape = [n for n in s.tape if not n.used]
    return results


def _fire_ready_hook(arr):
    """Invoke one array's grad-ready hook; a broken hook must never take
    the user's backward pass down with it (the scheduler side marks
    itself broken and the Trainer falls back to the serial reduce)."""
    hook = getattr(arr, "_grad_ready_hook", None)
    if hook is None:
        return
    try:
        hook(arr)
    except Exception:
        import logging
        logging.getLogger("graftlap").exception(
            "grad-ready hook raised; gradient delivery is unaffected "
            "but overlapped reduces fall back to the serial path")


def _deliver(arr, grads, as_ndarray=False):
    """Write one array's accumulated cotangent into its grad buffer.
    Returns True when a delivery actually happened (the caller's
    forward-order sweep stamps ``_tape_pos`` off it)."""
    if arr._grad is not None and arr._grad_req != "null" and id(arr) in grads:
        g = grads[id(arr)]
        if as_ndarray:
            g = g._read()
        if arr._grad_req == "add":
            arr._grad._write(arr._grad._read() + g)
        else:
            arr._grad._write(jnp.asarray(g, arr._grad._read().dtype))
        grads.pop(id(arr))
        return True
    return False


def _recorded_vjp(node, ct_nds):
    """Apply one node's backward as a *recorded* op (higher-order path).

    Builds g(primals..., cts...) = vjp(node.fn at primals)(cts) and runs it
    through the same record machinery as any eager op, so the produced
    input-cotangents carry tape edges to both the primal inputs and the
    incoming cotangents — exactly the dependency set the reference's
    backward-of-backward graph has (pass::Gradient applied twice).
    """
    from ..ndarray.ndarray import NDArray
    from ..ops.registry import Operator

    n_in = len(node.inputs)
    if node.fn is None:
        # no replayable function: first-order cotangents flow, but they
        # cannot be differentiated again — warn now, and raise only if
        # someone actually backprops through them (the tape-less NDArrays
        # below act as constants; _run_backward never revisits them)
        import warnings
        warnings.warn(
            "create_graph=True through %r: its backward is an opaque "
            "callback (autograd.Function), so gradients flowing through "
            "it are first-order only — a second backward treats them as "
            "constants. Use regular ops or mx.operator custom ops for "
            "true higher-order support." % node.op.name, stacklevel=3)
        raw = node.vjp(tuple(c._read() for c in ct_nds)
                       if len(ct_nds) > 1 else ct_nds[0]._read())
        return tuple(NDArray(g) if g is not None else None for g in raw)

    def gfun(*args):
        prim = args[:n_in]
        cts = args[n_in:]
        out, vjp_fn = jax.vjp(node.fn, *prim)
        ct = cts[0] if len(cts) == 1 else tuple(cts)
        res = vjp_fn(ct)
        # single-output nodes hand their vjp a bare leaf (tape convention)
        return res[0] if n_in == 1 else res

    all_inputs = list(node.inputs) + list(ct_nds)
    vals = [a._read() for a in all_inputs]
    out_vals, vjp2 = jax.vjp(gfun, *vals)
    if not isinstance(out_vals, tuple):
        out_vals = (out_vals,)
    outs = [NDArray(v) for v in out_vals]
    bop = Operator("_backward_" + node.op.name, gfun,
                   num_inputs=len(all_inputs), num_outputs=len(outs))
    _record(bop, all_inputs, outs, vjp2, fn=gfun)
    return outs


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """ref: autograd.py:243 / MXAutogradBackwardEx."""
    from ..telemetry import tracing as _ttracing
    with _ttracing.phase_span("bwd"):
        with _scope(training=train_mode):
            _run_backward(heads, head_grads, retain_graph, train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """ref: autograd.py:270 — return grads of heads w.r.t. variables."""
    from ..ndarray.ndarray import NDArray

    if retain_graph is None:
        retain_graph = create_graph
    # create_graph must record its own vjp/accumulation ops even when the
    # caller sits outside a record() scope (the reference's higher-order
    # backward always builds the grad-of-grad graph)
    with _scope(training=train_mode,
                recording=True if create_graph else None):
        raw = _run_backward(heads, head_grads, retain_graph, train_mode,
                            variables=variables, create_graph=create_graph)
    if create_graph:
        # already tape-connected NDArrays (see _recorded_vjp)
        return list(raw)
    return [NDArray(g, ctx=v._ctx) for g, v in zip(raw, variables)]


def get_symbol(x):
    """Trace history of x into a Symbol (ref: autograd.py get_symbol)."""
    from ..symbol import trace_to_symbol
    return trace_to_symbol(x)


class Function:
    """Custom differentiable function (ref: autograd.py:364 mx.autograd.Function).

    Subclass and implement forward(self, *inputs) and backward(self, *out_grads),
    both operating on NDArrays with pause() semantics inside.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        from ..ndarray.ndarray import NDArray
        from ..ops.registry import Operator

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if is_recording():
            fn_self = self

            def vjp(ct):
                cts = (ct,) if not isinstance(ct, tuple) else ct
                with pause():
                    from ..ndarray.ndarray import NDArray as ND
                    ct_nd = [ND(c) for c in cts]
                    in_grads = fn_self.backward(*ct_nd)
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(g._read() for g in in_grads)

            fake_op = Operator("_custom_function", lambda *a: a,
                               num_inputs=len(inputs), num_outputs=len(outs))
            _record(fake_op, list(inputs), outs, vjp)
        return outputs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
