"""Autograd: tape-based reverse-mode differentiation over eager ops.

TPU-native rebirth of src/imperative/imperative.cc (+ python/mxnet/autograd.py):

* ``record()/pause()/train_mode()/predict_mode()`` scopes == the reference's
  thread-local ``is_recording_/is_train_`` flags (imperative.cc:25-29).
* Each recorded eager op stores the ``jax.vjp`` closure of its own jitted
  fcompute — the tape IS the gradient graph, so there is no separate
  ``pass::Gradient`` construction step (imperative.cc:433): XLA already owns
  the per-op backward kernels.
* ``backward()`` walks the tape in reverse accumulating cotangents
  (RunGraph over the backward graph, imperative.cc:268).
* ``grad()`` with ``create_graph=True`` re-records each vjp application,
  giving higher-order gradients (parity with autograd.py:270).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "mark_variables",
           "backward", "grad", "get_symbol", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_recording):  # noqa: A002 - parity signature
    s = _st()
    prev = s.recording
    s.recording = bool(is_recording)
    return prev


def set_training(train_mode):
    s = _st()
    prev = s.training
    s.training = bool(train_mode)
    return prev


@contextmanager
def _scope(recording=None, training=None):
    s = _st()
    prev_r, prev_t = s.recording, s.training
    if recording is not None:
        s.recording = recording
    if training is not None:
        s.training = training
    try:
        yield
    finally:
        s.recording, s.training = prev_r, prev_t


def record(train_mode=True):
    """ref: autograd.py:93 record scope."""
    return _scope(recording=True, training=train_mode)


def pause(train_mode=False):
    return _scope(recording=False, training=train_mode)


def train_mode():
    return _scope(training=True)


def predict_mode():
    return _scope(training=False)


class TapeNode:
    __slots__ = ("op", "inputs", "outputs", "vjp", "used")

    def __init__(self, op, inputs, outputs, vjp):
        self.op = op
        self.inputs = inputs      # list[NDArray] (strong refs keep tape valid)
        self.outputs = outputs    # list[NDArray]
        self.vjp = vjp
        self.used = False


def _record(op, inputs, outputs, vjp_fn):
    """Called by ndarray.invoke under recording (RecordOp, imperative.cc:182)."""
    s = _st()
    node = TapeNode(op, inputs, outputs, vjp_fn)
    for i, o in enumerate(outputs):
        o._tape_ref = (node, i)
    s.tape.append(node)


def mark_variables(variables, gradients, grad_reqs="write"):
    """ref: imperative.cc:112 MarkVariables — attach grad buffers."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req


def _run_backward(heads, head_grads, retain_graph, train_mode, variables=None,
                  create_graph=False):
    s = _st()
    tape = s.tape
    grads: dict[int, object] = {}
    # seed
    for i, h in enumerate(heads):
        hg = None if head_grads is None else head_grads[i]
        if hg is None:
            seed = jnp.ones_like(h._read())
        else:
            seed = hg._read()
        grads[id(h)] = seed

    var_ids = None if variables is None else {id(v): v for v in variables}

    # reverse pass over the tape
    for node in reversed(tape):
        if not any(id(o) in grads for o in node.outputs):
            continue
        if node.used and not retain_graph:
            raise RuntimeError(
                "graph already backpropagated; use retain_graph=True "
                "(parity: mxnet 'hit a node twice' check)")
        out_cts = tuple(
            grads.get(id(o), jnp.zeros_like(o._read())) for o in node.outputs)
        ct = out_cts[0] if len(out_cts) == 1 else out_cts
        if create_graph:
            in_cts = _recorded_vjp(node, ct)
        else:
            in_cts = node.vjp(ct)
        for idx, (inp, g) in enumerate(zip(node.inputs, in_cts)):
            if idx in node.op.nograd_inputs or g is None:
                continue
            key = id(inp)
            if key in grads:
                grads[key] = grads[key] + g
            else:
                grads[key] = g
        if not retain_graph:
            node.used = True

    # deliver into .grad buffers (or return for grad())
    results = None
    if var_ids is not None:
        results = []
        for v in variables:
            g = grads.get(id(v))
            if g is None:
                g = jnp.zeros_like(v._read())
            results.append(g)
    for node in tape:
        for arr in node.inputs:
            _deliver(arr, grads)
    for h in heads:
        _deliver(h, grads)
    if not retain_graph and not create_graph:
        s.tape = [n for n in tape if not n.used]
    return results


def _deliver(arr, grads):
    if arr._grad is not None and arr._grad_req != "null" and id(arr) in grads:
        g = grads[id(arr)]
        if arr._grad_req == "add":
            arr._grad._write(arr._grad._read() + g)
        else:
            arr._grad._write(jnp.asarray(g, arr._grad._read().dtype))
        grads.pop(id(arr))


def _recorded_vjp(node, ct):
    """Apply a node's vjp while re-recording it on the tape (higher-order)."""
    from ..ndarray.ndarray import NDArray

    s = _st()
    # The cotangent may itself be an NDArray-producing recorded value; here we
    # treat it as a raw value and re-record the vjp application as one node.
    out_vals, vjp2 = jax.vjp(node.vjp, ct)
    return out_vals[0] if isinstance(out_vals, tuple) and len(out_vals) == 1 else out_vals


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """ref: autograd.py:243 / MXAutogradBackwardEx."""
    with _scope(training=train_mode):
        _run_backward(heads, head_grads, retain_graph, train_mode)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """ref: autograd.py:270 — return grads of heads w.r.t. variables."""
    from ..ndarray.ndarray import NDArray

    if retain_graph is None:
        retain_graph = create_graph
    with _scope(training=train_mode):
        raw = _run_backward(heads, head_grads, retain_graph, train_mode,
                            variables=variables, create_graph=create_graph)
    outs = [NDArray(g, ctx=v._ctx) for g, v in zip(raw, variables)]
    if create_graph:
        # re-record: make returned grads differentiable by replaying through
        # a recorded identity-of-vjp composite. We record one composite node
        # whose vjp is the full second-order vjp chain.
        _record_grad_graph(heads, variables, outs, head_grads)
    return outs


def _record_grad_graph(heads, variables, grad_outs, head_grads):
    """Record grads as outputs of a composite op so grads-of-grads work."""
    from ..ops.registry import Operator

    vals = [v._read() for v in variables]

    def composite(*var_vals):
        # rebuild forward functionally via jax.grad on a closure of the tape
        # — supported only for single-head scalar cases, the common pattern
        # (loss.backward style). Falls back silently otherwise.
        raise NotImplementedError

    # Higher-order support is handled through jax.vjp inside _recorded_vjp;
    # full replay-based re-recording lands with the symbolic executor where
    # the whole graph is available as one function.
    return


def get_symbol(x):
    """Trace history of x into a Symbol (ref: autograd.py get_symbol)."""
    from ..symbol import trace_to_symbol
    return trace_to_symbol(x)


class Function:
    """Custom differentiable function (ref: autograd.py:364 mx.autograd.Function).

    Subclass and implement forward(self, *inputs) and backward(self, *out_grads),
    both operating on NDArrays with pause() semantics inside.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def __call__(self, *inputs):
        from ..ndarray.ndarray import NDArray
        from ..ops.registry import Operator

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if is_recording():
            fn_self = self

            def vjp(ct):
                cts = (ct,) if not isinstance(ct, tuple) else ct
                with pause():
                    from ..ndarray.ndarray import NDArray as ND
                    ct_nd = [ND(c) for c in cts]
                    in_grads = fn_self.backward(*ct_nd)
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(g._read() for g in in_grads)

            fake_op = Operator("_custom_function", lambda *a: a,
                               num_inputs=len(inputs), num_outputs=len(outs))
            _record(fake_op, list(inputs), outs, vjp)
        return outputs

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError
