"""Mixture-of-Experts with expert parallelism.

A new capability mandated by SURVEY §2.4 (the reference, a 2018
framework, has no EP row to port — "EP via sharded gather/scatter —
these are *new capabilities*"): a switch-style MoE feed-forward block
whose stacked expert weights shard over the mesh "ep" axis.

Two dispatch modes behind one module interface:

* ``dispatch="dense"`` (default) — einsums over the expert dimension,
  ``combine[n,e] · (x[n,d] @ W[e,d,h])``, with ``e`` sharded.  GSPMD
  partitions the contraction and inserts the psum merging expert outputs
  over ICI.  Simple and exact for any top_k, but compute ∝ num_experts.
* ``dispatch="capacity"`` — the classic Switch formulation: top-1
  routing with per-expert capacity slots; token activations travel to
  their expert's device via explicit ``lax.all_to_all`` and back, so
  compute is independent of num_experts and overflow tokens are dropped
  (``last_drop_fraction`` reports the rate on eager calls).
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from .._jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..gluon.block import HybridBlock
from ..ndarray import NDArray

__all__ = ["ExpertParallelMoE"]


def _switch_body(x, gw, w1, w2, *, axis, num_experts, cap):
    """Per-device capacity-based Switch dispatch (tokens sharded over the
    ep axis, experts sharded over the ep axis).

    The classic Switch-Transformer formulation: each token picks its top-1
    expert; the first ``cap`` tokens per expert get a capacity slot, the
    rest are DROPPED (output 0 for the FFN branch); dispatched token
    activations travel to the expert's device via ``lax.all_to_all`` and
    the expert outputs ride the reverse all-to-all home.  Compute is
    O(tokens·d·h) — independent of num_experts — where the dense masked
    path pays num_experts×.
    """
    nloc = x.shape[0]
    logits = x @ gw                                      # (N_l, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (N_l,)
    onehot = jax.nn.one_hot(expert, num_experts, dtype=x.dtype)
    # position of each token in its expert's queue (arrival order)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot - onehot,
                  axis=-1).astype(jnp.int32)
    keep = (pos < cap).astype(x.dtype)                   # capacity gate
    disp = onehot * keep[:, None]                        # (N_l, E)
    slot = jax.nn.one_hot(pos, cap, dtype=x.dtype)       # (N_l, C)
    dispatch = jnp.einsum("ne,nc->nec", disp, slot)      # (N_l, E, C)
    ein = jnp.einsum("nec,nd->ecd", dispatch, x)         # (E, C, d)
    # ship each expert's slot block to the device that owns the expert
    ein = lax.all_to_all(ein, axis, split_axis=0, concat_axis=1,
                         tiled=True)                     # (E/P, P·C, d)
    h = jax.nn.relu(jnp.einsum("gcd,gdh->gch", ein, w1))
    y = jnp.einsum("gch,ghd->gcd", h, w2)                # (E/P, P·C, d)
    y = lax.all_to_all(y, axis, split_axis=1, concat_axis=0,
                       tiled=True)                       # (E, C, d)
    # Switch combine: scale by the selected expert's softmax probability so
    # the gating logits stay differentiable (a bare one-hot combine would
    # starve the router of gradient).
    sel_prob = jnp.sum(probs * onehot, axis=-1, keepdims=True)
    out = jnp.einsum("nec,ecd->nd", dispatch, y) * sel_prob
    dropped = 1.0 - jnp.sum(keep) / nloc
    # Switch aux load-balance loss: E · Σ_e f_e·P_e (f = dispatch fraction,
    # P = mean router prob); minimised by uniform routing.
    aux = num_experts * jnp.sum(jnp.mean(onehot, axis=0)
                                * jnp.mean(probs, axis=0))
    return out, dropped.reshape(1), aux.reshape(1)


def switch_moe_apply(x, gw, w1, w2, mesh, ep_axis="ep",
                     capacity_factor=1.25):
    """Capacity-dispatch MoE over ``mesh[ep_axis]``: returns
    ``(out, drop_frac_per_device, aux_loss_per_device)``.  Tokens are
    sharded over the ep axis for dispatch (N must divide by the axis
    size); expert weights arrive sharded on their leading expert dim."""
    num_experts = w1.shape[0]
    ep = mesh.shape[ep_axis]
    if x.shape[0] % ep:
        raise ValueError("token count %d not divisible by ep=%d"
                         % (x.shape[0], ep))
    if num_experts % ep:
        raise ValueError("num_experts %d not divisible by ep=%d"
                         % (num_experts, ep))
    nloc = x.shape[0] // ep
    cap = max(1, int(math.ceil(capacity_factor * nloc / num_experts)))
    fn = shard_map(
        functools.partial(_switch_body, axis=ep_axis,
                          num_experts=num_experts, cap=cap),
        mesh=mesh,
        in_specs=(P(ep_axis), P(), P(ep_axis), P(ep_axis)),
        out_specs=(P(ep_axis), P(ep_axis), P(ep_axis)),
        check_vma=False)
    return fn(x, gw, w1, w2)


class ExpertParallelMoE(HybridBlock):
    """Switch-style top-k MoE FFN (experts sharded over mesh axis "ep").

    Parameters live stacked: gate (d, E), expert weights (E, d, h) and
    (E, h, d).  Set ``ep_axis`` to the mesh axis name that shards the
    expert dimension (annotated on the parameters; DataParallelTrainer
    places them accordingly).
    """

    def __init__(self, hidden_size, num_experts, top_k=1, ep_axis="ep",
                 dispatch="dense", capacity_factor=1.25,
                 prefix=None, params=None, **kwargs):
        super().__init__(prefix=prefix, params=params, **kwargs)
        self._hidden = hidden_size
        self._num_experts = num_experts
        self._top_k = int(top_k)
        self._ep_axis = ep_axis
        if dispatch not in ("dense", "capacity"):
            raise ValueError("dispatch must be 'dense' or 'capacity', got %r"
                             % (dispatch,))
        if dispatch == "capacity" and self._top_k != 1:
            raise ValueError("capacity dispatch implements top-1 Switch "
                             "routing; use dispatch='dense' for top_k > 1")
        self._dispatch = dispatch
        self._capacity_factor = float(capacity_factor)
        self.last_drop_fraction = None  # updated on eager capacity calls
        self._last_aux = None           # Switch load-balance loss, lazy
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(0, num_experts),
                allow_deferred_init=True)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, 0, hidden_size),
                allow_deferred_init=True)
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, 0),
                allow_deferred_init=True)
        # shard the expert dimension over "ep": each device owns E/ep
        # experts' weights and their compute
        self.expert_w1.sharding = (ep_axis, None, None)
        self.expert_w2.sharding = (ep_axis, None, None)

    def _pre_infer(self, x):
        """Layer-local deferred-shape fill from the live input."""
        d = int(x.shape[-1])
        if self.gate_weight.shape[0] == 0:
            self.gate_weight.shape = (d, self._num_experts)
            self.expert_w1.shape = (self._num_experts, d, self._hidden)
            self.expert_w2.shape = (self._num_experts, self._hidden, d)

    def hybrid_forward(self, F, x, gate_weight=None, expert_w1=None,
                       expert_w2=None):
        """x: (N, d) → (N, d).  Top-k gating with probability-weighted
        combine; the expert einsums carry the sharded E dimension."""
        xv = x._read() if isinstance(x, NDArray) else x
        gw = gate_weight._read() if isinstance(gate_weight, NDArray) \
            else gate_weight
        w1 = expert_w1._read() if isinstance(expert_w1, NDArray) else expert_w1
        w2 = expert_w2._read() if isinstance(expert_w2, NDArray) else expert_w2

        if self._dispatch == "capacity":
            out = self._capacity_forward(xv, gw, w1, w2)
            return NDArray(out) if isinstance(x, NDArray) else out

        logits = xv @ gw                               # (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        if self._top_k == 1:
            # Switch combine: raw selected probability (renormalising a
            # single expert would collapse to 1.0 and starve the router
            # of gradient)
            onehot = jax.nn.one_hot(jnp.argmax(probs, axis=-1),
                                    self._num_experts, dtype=xv.dtype)
            combine = probs * onehot
        elif self._top_k < self._num_experts:
            top_vals, _ = jax.lax.top_k(probs, self._top_k)
            thresh = top_vals[..., -1:]
            mask = probs >= thresh
            gated = jnp.where(mask, probs, 0.0)
            # renormalize over the selected experts (top-k combine)
            combine = gated / jnp.maximum(
                gated.sum(-1, keepdims=True), 1e-9)
        else:
            combine = probs
        self._store_aux(combine, probs)
        # per-expert FFN, expert dim sharded: h[e] = relu(x @ W1[e]) @ W2[e]
        h = jax.nn.relu(jnp.einsum("nd,edh->neh", xv, w1))
        y = jnp.einsum("neh,ehd->ned", h, w2)
        out = jnp.einsum("ne,ned->nd", combine, y)
        return NDArray(out) if isinstance(x, NDArray) else out

    @property
    def last_aux_loss(self):
        """Switch load-balance loss E·Σ f_e·P_e from the last eager call
        (materialised lazily — reading it may sync with the device)."""
        v = self._last_aux
        return None if v is None else float(v)

    @last_aux_loss.setter
    def last_aux_loss(self, v):
        self._last_aux = v

    def _store_aux(self, combine, probs):
        """Stash the load-balance loss on eager calls without forcing a
        device->host sync on the forward path.  Dispatch fraction uses the
        top-1 choice (GShard convention) so the stat stays meaningful even
        for soft routing, where every combine entry is nonzero."""
        if isinstance(probs, jax.core.Tracer):
            return
        top = jnp.argmax(probs, axis=-1)
        frac = jnp.mean(jax.nn.one_hot(top, self._num_experts,
                                       dtype=probs.dtype), axis=0)
        self._last_aux = self._num_experts * jnp.sum(
            frac * jnp.mean(probs, axis=0))

    def _capacity_forward(self, xv, gw, w1, w2):
        """Switch all-to-all dispatch over the scoped mesh's ep axis.
        Eager calls place operands on the mesh, run, and gather the output
        home (storing ``last_drop_fraction``); inside an enclosing jit the
        caller's shardings flow through and stats stay on device."""
        from .mesh import current_mesh, dispatch_on_mesh, gather_home
        mesh = current_mesh(required=True)
        if self._ep_axis not in mesh.axis_names:
            raise ValueError("mesh %s has no axis %r for capacity dispatch"
                             % (mesh.axis_names, self._ep_axis))
        ep = self._ep_axis
        (out, drops, aux), eager = dispatch_on_mesh(
            lambda a, b, c, d: switch_moe_apply(a, b, c, d, mesh, ep,
                                                self._capacity_factor),
            mesh, (P(ep), P(), P(ep), P(ep)), xv, gw, w1, w2)
        if eager:
            if not isinstance(drops, jax.core.Tracer):
                # concrete eager call; under the eager tape's vjp trace
                # drops is a tracer — stats stay at their last value
                self.last_drop_fraction = float(
                    np.mean(jax.device_get(drops)))
                self.last_aux_loss = float(np.mean(jax.device_get(aux)))
            return gather_home(out, mesh)
        return out
