"""Mixture-of-Experts with expert parallelism.

A new capability mandated by SURVEY §2.4 (the reference, a 2018
framework, has no EP row to port — "EP via sharded gather/scatter —
these are *new capabilities*"): a switch-style MoE feed-forward block
whose stacked expert weights shard over the mesh "ep" axis.

Design (TPU-first): dispatch is expressed as einsums over the expert
dimension — ``combine[n,e] · (x[n,d] @ W[e,d,h])`` — with the ``e``
dimension sharded.  GSPMD partitions the expert contraction so each
device computes only its local experts and inserts the psum that merges
expert outputs over ICI; no hand-written all-to-all.  (A capacity-based
token-routing variant trades the masked compute for explicit
``all_to_all`` — the classic Switch formulation — and drops in behind
the same module interface.)
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..gluon.block import HybridBlock
from ..ndarray import NDArray

__all__ = ["ExpertParallelMoE"]


class ExpertParallelMoE(HybridBlock):
    """Switch-style top-k MoE FFN (experts sharded over mesh axis "ep").

    Parameters live stacked: gate (d, E), expert weights (E, d, h) and
    (E, h, d).  Set ``ep_axis`` to the mesh axis name that shards the
    expert dimension (annotated on the parameters; DataParallelTrainer
    places them accordingly).
    """

    def __init__(self, hidden_size, num_experts, top_k=1, ep_axis="ep",
                 prefix=None, params=None, **kwargs):
        super().__init__(prefix=prefix, params=params, **kwargs)
        self._hidden = hidden_size
        self._num_experts = num_experts
        self._top_k = int(top_k)
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(0, num_experts),
                allow_deferred_init=True)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, 0, hidden_size),
                allow_deferred_init=True)
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, 0),
                allow_deferred_init=True)
        # shard the expert dimension over "ep": each device owns E/ep
        # experts' weights and their compute
        self.expert_w1.sharding = (ep_axis, None, None)
        self.expert_w2.sharding = (ep_axis, None, None)

    def _pre_infer(self, x):
        """Layer-local deferred-shape fill from the live input."""
        d = int(x.shape[-1])
        if self.gate_weight.shape[0] == 0:
            self.gate_weight.shape = (d, self._num_experts)
            self.expert_w1.shape = (self._num_experts, d, self._hidden)
            self.expert_w2.shape = (self._num_experts, self._hidden, d)

    def hybrid_forward(self, F, x, gate_weight=None, expert_w1=None,
                       expert_w2=None):
        """x: (N, d) → (N, d).  Top-k gating with probability-weighted
        combine; the expert einsums carry the sharded E dimension."""
        xv = x._read() if isinstance(x, NDArray) else x
        gw = gate_weight._read() if isinstance(gate_weight, NDArray) \
            else gate_weight
        w1 = expert_w1._read() if isinstance(expert_w1, NDArray) else expert_w1
        w2 = expert_w2._read() if isinstance(expert_w2, NDArray) else expert_w2

        logits = xv @ gw                               # (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        if self._top_k < self._num_experts:
            top_vals, _ = jax.lax.top_k(probs, self._top_k)
            thresh = top_vals[..., -1:]
            mask = probs >= thresh
            gated = jnp.where(mask, probs, 0.0)
            # renormalize over the selected experts (Switch/Top-k combine)
            combine = gated / jnp.maximum(
                gated.sum(-1, keepdims=True), 1e-9)
        else:
            combine = probs
        # per-expert FFN, expert dim sharded: h[e] = relu(x @ W1[e]) @ W2[e]
        h = jax.nn.relu(jnp.einsum("nd,edh->neh", xv, w1))
        y = jnp.einsum("neh,ehd->ned", h, w2)
        out = jnp.einsum("ne,ned->nd", combine, y)
        return NDArray(out) if isinstance(x, NDArray) else out
