"""Device mesh construction and axis conventions.

The reference assigns work to devices by Context lists
(DataParallelExecutorGroup) and `ctx_group` attrs (PlaceDevice pass);
TPU-natively the device topology is a named ``jax.sharding.Mesh`` and
placement is a sharding annotation.  Axis name conventions used throughout
the framework:

  "dp" — data parallel (batch dim)           ⇔ KVStore local/device/dist
  "tp" — tensor/model parallel               ⇔ ctx_group model parallelism
  "pp" — pipeline stages                     ⇔ (new capability)
  "sp" — sequence/context parallel           ⇔ (new capability, ring attn)
  "ep" — expert parallel                     ⇔ (new capability)
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "data_parallel_mesh", "P", "NamedSharding", "Mesh"]

P = PartitionSpec


def make_mesh(axis_sizes, devices=None):
    """Build a Mesh from {"dp": 4, "tp": 2, ...} (row-major over devices)."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(v) for v in axis_sizes.values())
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(sizes))
    assert len(devices) >= n, \
        "mesh needs %d devices, have %d" % (n, len(devices))
    arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(num_devices=None, devices=None):
    """1-D dp mesh over all (or the first N) devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh({"dp": len(devices)}, devices)
