"""Device mesh construction and axis conventions.

The reference assigns work to devices by Context lists
(DataParallelExecutorGroup) and `ctx_group` attrs (PlaceDevice pass);
TPU-natively the device topology is a named ``jax.sharding.Mesh`` and
placement is a sharding annotation.  Axis name conventions used throughout
the framework:

  "dp" — data parallel (batch dim)           ⇔ KVStore local/device/dist
  "tp" — tensor/model parallel               ⇔ ctx_group model parallelism
  "pp" — pipeline stages                     ⇔ (new capability)
  "sp" — sequence/context parallel           ⇔ (new capability, ring attn)
  "ep" — expert parallel                     ⇔ (new capability)
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "data_parallel_mesh", "P", "NamedSharding", "Mesh",
           "use_mesh", "current_mesh"]

P = PartitionSpec

import threading as _threading

_mesh_tls = _threading.local()


def _stack():
    # thread-local: concurrent trainers/eval threads must not see each
    # other's scoped mesh (same reason jax's mesh managers are TLS)
    if not hasattr(_mesh_tls, "stack"):
        _mesh_tls.stack = []
    return _mesh_tls.stack


class use_mesh(object):
    """Scope a mesh as the framework-wide default: layers that need a
    device topology (gluon.nn.MultiHeadAttention's seq_axis path, the
    ring-attention op) resolve it from here when not passed explicitly —
    the role Context lists played for the reference's executors, for mesh
    axes.  Usable as a context manager or activated for the whole program
    via ``use_mesh(mesh).activate()``."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _stack().append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _stack().pop()

    def activate(self):
        _stack().append(self.mesh)
        return self.mesh


def is_staging(x):
    """True when ``x`` is a tracer from an enclosing jit's staging trace
    (as opposed to a concrete array OR an eager-autodiff tracer whose
    primitives execute immediately)."""
    try:
        from jax.interpreters.partial_eval import DynamicJaxprTracer
    except ImportError:  # pragma: no cover - jax internals moved
        return False
    return isinstance(x, DynamicJaxprTracer)


def dispatch_on_mesh(fn, mesh, in_specs, *arrays):
    """Run a collective-bearing ``fn(*arrays)`` correctly in both worlds.

    Staging inside an enclosing jit: call straight through — the caller's
    shardings flow in and outputs stay sharded.  Eager (including the
    eager autograd tape, whose vjp primitives execute immediately): place
    each operand per its PartitionSpec on ``mesh`` first.  Returns
    ``(outputs, eager)``; eager callers usually want ``gather_home`` on
    array outputs so downstream single-device ops see plain arrays.
    """
    if is_staging(arrays[0]):
        return fn(*arrays), False
    placed = [jax.device_put(a, NamedSharding(mesh, s))
              for a, s in zip(arrays, in_specs)]
    return fn(*placed), True


def gather_home(x, mesh):
    """Pull a mesh-sharded eager result onto one device (traceable and
    transposable, so the tape differentiates through it)."""
    return jax.device_put(x, mesh.devices.flat[0])


def current_mesh(required=False):
    """The innermost scoped mesh, or None (raise when ``required``)."""
    if _stack():
        return _stack()[-1]
    if required:
        raise RuntimeError(
            "no device mesh in scope — wrap the call in "
            "`with parallel.use_mesh(make_mesh({...})):` or pass mesh=")
    return None


def make_mesh(axis_sizes, devices=None):
    """Build a Mesh from {"dp": 4, "tp": 2, ...} (row-major over devices)."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(v) for v in axis_sizes.values())
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(sizes))
    assert len(devices) >= n, \
        "mesh needs %d devices, have %d" % (n, len(devices))
    arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(num_devices=None, devices=None):
    """1-D dp mesh over all (or the first N) devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return make_mesh({"dp": len(devices)}, devices)
