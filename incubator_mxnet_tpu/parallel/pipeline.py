"""Pipeline parallelism: GPipe-style microbatch schedule over the "pp" axis.

The reference has no explicit pipeline scheduler — its async engine
dataflow-pipelines model-parallel graphs implicitly (SURVEY §2.4 row
'Pipeline parallelism'). TPU-natively the schedule must be explicit and
static: each mesh "pp" device holds one stage's parameters; activations hop
stage→stage via ``ppermute`` over ICI; the (num_micro + num_stages - 1)-step
loop is a ``lax.fori_loop`` so XLA overlaps the hop with the next
microbatch's compute.

Constraints (standard for this formulation): every stage maps activations
of one shape to the same shape (transformer-block-like), and
num_microbatches ≥ 1.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["pipeline_apply", "pipeline_train_step", "make_pipeline_trainer"]


def _pp_body(params, xs, stage_fn, axis_name):
    """Per-device body. params: this stage's params (leading pp axis already
    split away by shard_map). xs: (n_micro, ...) microbatches — only stage
    0 reads them; outputs: (n_micro, ...) — only the last stage's are real."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], params)   # drop stacked pp dim
    n_micro = xs.shape[0]
    T = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    mb_shape = xs.shape[1:]
    received = jnp.zeros(mb_shape, xs.dtype)
    outputs = jnp.zeros_like(xs)

    def step(t, carry):
        received, outputs = carry
        inject = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        act_in = jnp.where(idx == 0, inject, received)
        act_out = stage_fn(params, act_in)
        # last stage records its result for microbatch t-(n-1)
        out_slot = jnp.clip(t - (n - 1), 0, n_micro - 1)
        record = (idx == n - 1) & (t >= n - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(record,
                      act_out,
                      lax.dynamic_index_in_dim(outputs, out_slot, 0,
                                               keepdims=False)),
            out_slot, axis=0)
        received = lax.ppermute(act_out, axis_name, perm)
        return received, outputs

    _, outputs = lax.fori_loop(0, T, step, (received, outputs))
    # broadcast last stage's outputs to every device (so out_specs can be
    # replicated over pp)
    outputs = lax.psum(jnp.where(idx == n - 1, outputs, 0.0), axis_name)
    return outputs


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   num_microbatches=None):
    """Run x through num_stages stages, stage i using stacked_params[...][i].

    stacked_params: pytree whose leaves have a leading axis of size
    mesh.shape[axis] (one slice per stage). x: (batch, ...) global input.
    Returns (batch, ...) output of the final stage.
    """
    n_stages = mesh.shape[axis]
    if num_microbatches is None:
        num_microbatches = n_stages
    B = x.shape[0]
    assert B % num_microbatches == 0, \
        "batch %d not divisible into %d microbatches" % (B, num_microbatches)
    mb = B // num_microbatches
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])

    p_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        functools.partial(_pp_body, stage_fn=stage_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(p_spec, P()),
        out_specs=P(),
        check_vma=False)
    out = fn(stacked_params, xs)
    return out.reshape((B,) + out.shape[2:])


def pipeline_train_step(stage_fn, stacked_params, x, y, loss_fn, mesh,
                        axis="pp", num_microbatches=None):
    """One pipeline *training* step: microbatched forward through the
    stages, loss on the last stage's output, backward re-traversing the
    schedule in reverse (the transpose of each ``ppermute`` hop is the
    opposite hop, so gradient activations ride the ring backwards), with
    gradient accumulation across microbatches falling out of the loop
    transpose.  Returns ``(loss, grads)`` with ``grads`` shaped like
    ``stacked_params`` (leading stage axis).

    The reference has no pipeline scheduler to mirror (SURVEY §2.4); this
    is the capability mandated by SURVEY §7 phase 11.
    """

    def objective(params):
        out = pipeline_apply(stage_fn, params, x, mesh, axis=axis,
                             num_microbatches=num_microbatches)
        return jnp.mean(loss_fn(out, y))

    return jax.value_and_grad(objective)(stacked_params)


def make_pipeline_trainer(stage_fn, loss_fn, mesh, axis="pp",
                          num_microbatches=None, learning_rate=0.01):
    """Jitted GPipe SGD trainer: returns ``train(params, x, y) ->
    (params, loss)`` with stage-sharded donated params."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train(params, x, y):
        loss, grads = pipeline_train_step(stage_fn, params, x, y, loss_fn,
                                          mesh, axis=axis,
                                          num_microbatches=num_microbatches)
        params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
        return params, loss

    return train
