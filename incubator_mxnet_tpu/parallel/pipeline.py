"""Pipeline parallelism: GPipe-style microbatch schedule over the "pp" axis.

The reference has no explicit pipeline scheduler — its async engine
dataflow-pipelines model-parallel graphs implicitly (SURVEY §2.4 row
'Pipeline parallelism'). TPU-natively the schedule must be explicit and
static: each mesh "pp" device holds one stage's parameters; activations hop
stage→stage via ``ppermute`` over ICI; the (num_micro + num_stages - 1)-step
loop is a ``lax.fori_loop`` so XLA overlaps the hop with the next
microbatch's compute.

Constraints (standard for this formulation): every stage maps activations
of one shape to the same shape (transformer-block-like), and
num_microbatches ≥ 1.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from .._jax_compat import shard_map

__all__ = ["pipeline_apply", "pipeline_train_step", "make_pipeline_trainer",
           "PipelineTrainer"]


def _pp_body(params, xs, stage_fn, axis_name):
    """Per-device body. params: this stage's params (leading pp axis already
    split away by shard_map). xs: (n_micro, ...) microbatches — only stage
    0 reads them; outputs: (n_micro, ...) — only the last stage's are real."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    params = jax.tree.map(lambda p: p[0], params)   # drop stacked pp dim
    n_micro = xs.shape[0]
    T = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    mb_shape = xs.shape[1:]
    received = jnp.zeros(mb_shape, xs.dtype)
    outputs = jnp.zeros_like(xs)

    def step(t, carry):
        received, outputs = carry
        inject = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
        act_in = jnp.where(idx == 0, inject, received)
        act_out = stage_fn(params, act_in)
        # last stage records its result for microbatch t-(n-1)
        out_slot = jnp.clip(t - (n - 1), 0, n_micro - 1)
        record = (idx == n - 1) & (t >= n - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(record,
                      act_out,
                      lax.dynamic_index_in_dim(outputs, out_slot, 0,
                                               keepdims=False)),
            out_slot, axis=0)
        received = lax.ppermute(act_out, axis_name, perm)
        return received, outputs

    _, outputs = lax.fori_loop(0, T, step, (received, outputs))
    # broadcast last stage's outputs to every device (so out_specs can be
    # replicated over pp)
    outputs = lax.psum(jnp.where(idx == n - 1, outputs, 0.0), axis_name)
    return outputs


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   num_microbatches=None):
    """Run x through num_stages stages, stage i using stacked_params[...][i].

    stacked_params: pytree whose leaves have a leading axis of size
    mesh.shape[axis] (one slice per stage). x: (batch, ...) global input.
    Returns (batch, ...) output of the final stage.
    """
    n_stages = mesh.shape[axis]
    if num_microbatches is None:
        num_microbatches = n_stages
    B = x.shape[0]
    assert B % num_microbatches == 0, \
        "batch %d not divisible into %d microbatches" % (B, num_microbatches)
    mb = B // num_microbatches
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])

    p_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    fn = shard_map(
        functools.partial(_pp_body, stage_fn=stage_fn, axis_name=axis),
        mesh=mesh,
        in_specs=(p_spec, P()),
        out_specs=P(),
        check_vma=False)
    out = fn(stacked_params, xs)
    return out.reshape((B,) + out.shape[2:])


def pipeline_train_step(stage_fn, stacked_params, x, y, loss_fn, mesh,
                        axis="pp", num_microbatches=None):
    """One pipeline *training* step: microbatched forward through the
    stages, loss on the last stage's output, backward re-traversing the
    schedule in reverse (the transpose of each ``ppermute`` hop is the
    opposite hop, so gradient activations ride the ring backwards), with
    gradient accumulation across microbatches falling out of the loop
    transpose.  Returns ``(loss, grads)`` with ``grads`` shaped like
    ``stacked_params`` (leading stage axis).

    The reference has no pipeline scheduler to mirror (SURVEY §2.4); this
    is the capability mandated by SURVEY §7 phase 11.
    """

    def objective(params):
        out = pipeline_apply(stage_fn, params, x, mesh, axis=axis,
                             num_microbatches=num_microbatches)
        return jnp.mean(loss_fn(out, y))

    return jax.value_and_grad(objective)(stacked_params)


def make_pipeline_trainer(stage_fn, loss_fn, mesh, axis="pp",
                          num_microbatches=None, learning_rate=0.01):
    """Jitted GPipe SGD trainer: returns ``train(params, x, y) ->
    (params, loss)`` with stage-sharded donated params."""

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train(params, x, y):
        loss, grads = pipeline_train_step(stage_fn, params, x, y, loss_fn,
                                          mesh, axis=axis,
                                          num_microbatches=num_microbatches)
        params = jax.tree.map(lambda p, g: p - learning_rate * g,
                              params, grads)
        return params, loss

    return train


def _run_block(block, vals_by_name, x, train=True):
    """Functionalize one Gluon block: run it on a jax array with parameter
    values substituted (the DataParallelTrainer tracing pattern)."""
    from ..ndarray import NDArray
    from .. import autograd
    shadows = {n: NDArray(v) for n, v in vals_by_name.items()}
    with autograd._scope(recording=False, training=train):
        with block._trace_params(shadows):
            out = block.hybrid_forward_dispatch(NDArray(x))
    return out._read()


class PipelineTrainer(object):
    """GPipe training for a Gluon ``HybridSequential`` of identical stages.

    The round-2 gap this closes: pipeline parallelism existed only as a
    raw ``stage(params, x)`` function (make_pipeline_trainer) a framework
    user could not reach from a Block.  Here the stages ARE Gluon blocks:

        body = nn.HybridSequential()
        for _ in range(n_stages):
            body.add(TransformerBlock(...))        # identical structure
        trainer = PipelineTrainer(body, loss, mesh, pre=embed, post=head)
        loss = trainer.step(x, y)

    Each mesh "pp" device holds ONE stage's parameters (leaves stacked on
    a leading stage axis, sharded over the pipeline axis); activations hop
    stage-to-stage via ppermute; backward re-traverses the schedule in
    reverse (pipeline_train_step).  ``pre``/``post`` blocks (embedding /
    head — usually structurally different from the body stages) run
    replicated outside the ring.

    Constraints (the standard static-schedule formulation): body stages
    must be structurally identical (same param shapes, activation shape
    preserved); stochastic layers (Dropout) are not supported inside the
    scheduled body; BatchNorm aux-state updates inside the body are
    dropped.  Optimizer: SGD (reference Module-style lr).
    """

    def __init__(self, net, loss, mesh=None, axis="pp", num_microbatches=None,
                 learning_rate=0.01, pre=None, post=None):
        from .mesh import current_mesh
        self.net = net
        self.loss = loss
        self.mesh = mesh if mesh is not None else current_mesh(required=True)
        self.axis = axis
        self.num_microbatches = num_microbatches
        self.learning_rate = learning_rate
        self.pre = pre
        self.post = post
        self._stages = list(net._children)
        n = self.mesh.shape[axis]
        if len(self._stages) != n:
            raise ValueError(
                "net has %d stage blocks but mesh axis %r has %d devices"
                % (len(self._stages), axis, n))
        self._state = None
        self._jit = None

    # -- parameter plumbing ------------------------------------------------
    def _gather(self, example_x):
        from jax.sharding import NamedSharding
        from ..ndarray import NDArray
        x = example_x
        if self.pre is not None:
            x = self.pre(x)
        for blk in self._stages:
            x = blk(x)          # resolves deferred shapes stage by stage
        if self.post is not None:
            self.post(x)
        stage_vals = []
        template = self._stages[0]
        for blk in self._stages:
            vals = [p.data()._read() for p in blk.collect_params().values()]
            if type(blk) is not type(template):
                raise ValueError(
                    "pipeline stages must be the same block type: %s vs %s"
                    % (type(template).__name__, type(blk).__name__))
            if stage_vals and [v.shape for v in vals] != \
                    [v.shape for v in stage_vals[0]]:
                raise ValueError(
                    "pipeline stages are not structurally identical: %s vs "
                    "%s" % ([v.shape for v in stage_vals[0]],
                            [v.shape for v in vals]))
            stage_vals.append(vals)
        # the schedule executes EVERY stage through stage 0's forward
        # function — same shapes is not enough (Dense(tanh) vs Dense(relu)
        # would silently compute the wrong model).  Probe: each stage's
        # own forward must equal the template driven by its params.
        probe = example_x
        if self.pre is not None:
            probe = self.pre(probe)
        pv = probe._read()
        names = list(template.collect_params().keys())
        for blk, vals in zip(self._stages[1:], stage_vals[1:]):
            # both sides run through _run_block (same train mode), else a
            # training-sensitive layer (BatchNorm) would falsely differ
            own_names = list(blk.collect_params().keys())
            own = np.asarray(
                _run_block(blk, dict(zip(own_names, vals)), pv))
            via_tmpl = np.asarray(
                _run_block(template, dict(zip(names, vals)), pv))
            if not np.allclose(own, via_tmpl, rtol=1e-5, atol=1e-6):
                raise ValueError(
                    "pipeline stage %r computes differently from stage 0 "
                    "despite identical param shapes (e.g. a different "
                    "activation/config) — the GPipe schedule requires "
                    "functionally identical stages" % (blk.name,))
        stacked = [jnp.stack([sv[j] for sv in stage_vals])
                   for j in range(len(stage_vals[0]))]
        stage_sh = NamedSharding(self.mesh, P(self.axis))
        repl = NamedSharding(self.mesh, P())
        self._stage_names = [list(b.collect_params().keys())
                             for b in self._stages]
        self._template_names = list(
            self._stages[0].collect_params().keys())
        state = {
            "stages": [jax.device_put(s, stage_sh) for s in stacked],
            "pre": {n: jax.device_put(p.data()._read(), repl)
                    for n, p in (self.pre.collect_params().items()
                                 if self.pre is not None else [])},
            "post": {n: jax.device_put(p.data()._read(), repl)
                     for n, p in (self.post.collect_params().items()
                                  if self.post is not None else [])},
        }
        self._state = state

    def _stage_fn(self):
        template = self._stages[0]
        names = self._template_names

        def fn(leaves, act):
            vals = dict(zip(names, leaves))
            return _run_block(template, vals, act)
        return fn

    def _build_jit(self):
        from jax.sharding import NamedSharding
        mesh, axis = self.mesh, self.axis
        stage_sh = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        pre_blk, post_blk, loss_blk = self.pre, self.post, self.loss
        stage_fn = self._stage_fn()
        n_micro = self.num_microbatches

        def objective(state, x, y):
            from ..ndarray import NDArray
            if pre_blk is not None:
                x = _run_block(pre_blk, state["pre"], x)
            out = pipeline_apply(stage_fn, state["stages"], x, mesh,
                                 axis=axis, num_microbatches=n_micro)
            if post_blk is not None:
                out = _run_block(post_blk, state["post"], out)
            per = loss_blk(NDArray(out), NDArray(y))
            return jnp.mean(per._read())

        # lr rides as a traced OPERAND (GL305): baking self.learning_rate
        # here would silently pin the schedule to its _build_jit-time
        # value — the exact constant-freeze the whole-step compiled path
        # (step_compile.py) already avoids for lr/wd/rescale
        def step(state, x, y, lr):
            loss, grads = jax.value_and_grad(objective)(state, x, y)
            new_state = jax.tree.map(lambda p, g: p - lr * g, state, grads)
            return new_state, loss

        shardings = {"stages": [stage_sh] * len(self._state["stages"]),
                     "pre": {n: repl for n in self._state["pre"]},
                     "post": {n: repl for n in self._state["post"]}}
        self._jit = jax.jit(step,
                            in_shardings=(shardings, repl, repl, repl),
                            out_shardings=(shardings, repl),
                            donate_argnums=(0,))

    # -- public surface ----------------------------------------------------
    def step(self, data, label):
        """One pipeline-parallel training step; returns the device loss."""
        from ..ndarray import NDArray
        from .mesh import use_mesh
        x = data._read() if isinstance(data, NDArray) else jnp.asarray(data)
        y = label._read() if isinstance(label, NDArray) else jnp.asarray(label)
        if self._state is None:
            self._gather(NDArray(x))
            self._build_jit()
        with use_mesh(self.mesh):
            self._state, loss = self._jit(
                self._state, x, y,
                jnp.asarray(self.learning_rate, jnp.float32))
        return loss

    def sync_params(self):
        """Write trained values back into the Gluon blocks."""
        from ..ndarray import NDArray
        for j, name0 in enumerate(self._template_names):
            stacked = jax.device_get(self._state["stages"][j])
            for i, blk in enumerate(self._stages):
                pname = self._stage_names[i][j]
                blk.collect_params()[pname].data()._write(
                    jnp.asarray(stacked[i]))
        for blk, key in ((self.pre, "pre"), (self.post, "post")):
            if blk is None:
                continue
            for n, p in blk.collect_params().items():
                p.data()._write(jnp.asarray(
                    jax.device_get(self._state[key][n])))
