"""Parallelism: device meshes, sharded training steps, distributed init.

TPU-native replacement for the reference's KVStore/Comm/ps-lite stack
(SURVEY §2.4): instead of explicit reduce/broadcast engine ops, parallelism
is expressed as jax.sharding over a Mesh and XLA inserts the collectives
(psum over ICI intra-slice, DCN collectives across slices).

Modules:
  mesh   — Mesh construction + named axis conventions (dp/tp/pp/sp/ep)
  dist   — multi-host process bootstrap (jax.distributed), rank/barrier,
           DistKVStore (the dist_sync/dist_async façade)
  data_parallel — DataParallelTrainer: pjit'd train step, batch-sharded
"""
from . import mesh
from . import dist
from .mesh import make_mesh, data_parallel_mesh, use_mesh, current_mesh
from .data_parallel import DataParallelTrainer
from .moe import ExpertParallelMoE
from .pipeline import PipelineTrainer
from .ring_attention import ring_attention, ring_attention_sharded
