"""Host-side parameter service: real ``dist_async`` semantics.

The reference's async mode is a ps-lite server applying each worker's
push the moment it arrives (src/kvstore/kvstore_dist_server.h:113-314:
``DataHandleEx`` dispatch, async branch at :306-314, the pickled
optimizer executed server-side via the kController command channel).
XLA's synchronous SPMD model cannot express that — so, exactly as the
reference does, the asynchronous state lives on a HOST service:

* rank 0 runs a ``ParameterServer`` thread — a pickle-framed TCP
  server holding the authoritative f32 weights and applying the
  (pickled, ``set_optimizer``-shipped) optimizer to every arriving
  gradient immediately: no barrier, no merge window, pure async.
* every worker's ``DistKVStore("dist_async")`` connects as a client:
  ``push`` ships the gradient and returns, ``pull`` fetches whatever
  the weights are *right now* — staleness included, which is the whole
  point of async SGD.
* the server address travels through the jax.distributed coordination
  service's key-value store (the Postoffice/scheduler's successor), so
  launch topology stays tools/launch.py with zero extra flags.

Scale-out shape (round 4): ``ServerGroup`` runs N server threads and
``GroupClient`` shards keys across them; arrays bigger than
``MXTPU_KVSTORE_BIGARRAY_BOUND`` (default 1M elements) are row-sliced
across ALL servers — the reference's big-array sharding
(kvstore_dist.h MXNET_KVSTORE_BIGARRAY_BOUND).  Clients heartbeat the
group; ``dead_nodes()`` reports workers whose beats stopped
(kvstore_dist.h:109-115 num_dead_nodes).  ``pull_rows`` ships ONLY the
requested rows (kvstore_dist_server.h:223 row_sparse handling).

SECURITY: the wire is UNAUTHENTICATED pickled TCP — deserializing a
pickle executes arbitrary code, so anyone who can reach the port owns
the process.  Single-host runs therefore bind loopback by default; the
server only listens on 0.0.0.0 when multi-host env vars are present
(MX_PS_HOST, or a remote MX_COORDINATOR), and MX_PS_BIND overrides the
choice.  Bind only on trusted/isolated networks (the same trust model
ps-lite's plain ZMQ wire assumes); this transport is a prototype-grade
stand-in, not a hardened service.
"""
from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time
import zlib

import numpy as np

__all__ = ["ParameterServer", "PSClient", "ServerGroup", "GroupClient",
           "publish_address", "lookup_address", "BIGARRAY_BOUND",
           "rpc_timeout", "rpc_retries", "rpc_backoff_ms"]

_LEN = struct.Struct("<Q")


# -- graftarmor wire policy (docs/robustness.md) ----------------------------

def rpc_timeout():
    """GRAFT_RPC_TIMEOUT: connect AND per-call socket timeout in seconds
    (default 60 — the old hardcoded connect timeout, now env-driven)."""
    try:
        t = float(os.environ.get("GRAFT_RPC_TIMEOUT", "60"))
    except ValueError:
        return 60.0
    return t if t > 0 else None


def rpc_retries():
    """GRAFT_RPC_RETRIES: retry budget AFTER the first attempt
    (default 3, so 4 attempts total; 0 restores fail-on-first-error)."""
    try:
        return max(0, int(os.environ.get("GRAFT_RPC_RETRIES", "3")))
    except ValueError:
        return 3


def rpc_backoff_ms():
    """GRAFT_RPC_BACKOFF_MS: base backoff between retries (default 50).
    The sleep doubles per attempt, caps at 2s, and is jittered to
    [0.5x, 1.5x) so a worker fleet never retries in phase."""
    try:
        return max(0.0, float(os.environ.get("GRAFT_RPC_BACKOFF_MS", "50")))
    except ValueError:
        return 50.0


def BIGARRAY_BOUND():
    import os
    return int(os.environ.get("MXTPU_KVSTORE_BIGARRAY_BOUND", str(1 << 20)))


def _advertised_host():
    import os
    env = os.environ.get("MX_PS_HOST")
    if env:
        return env
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _default_bind_host():
    """Pick the listening interface: MX_PS_BIND wins; any launched
    distributed run (MX_PS_HOST, MX_COORDINATOR, or an initialized
    multi-process jax.distributed) must accept external connections;
    otherwise keep the wire on loopback — the pickle protocol is
    unauthenticated, so a plain single-process run should never expose
    a network-reachable port."""
    import os
    env = os.environ.get("MX_PS_BIND")
    if env:
        return env
    if os.environ.get("MX_PS_HOST") or os.environ.get("MX_COORDINATOR"):
        return "0.0.0.0"
    try:
        import jax
        if jax.process_count() > 1:
            return "0.0.0.0"
    except Exception:
        pass
    return "127.0.0.1"


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class ParameterServer(object):
    """The server role (runs as a daemon thread inside rank 0's process —
    the reference would run it in dedicated server processes; one thread
    suffices for the single-server topology)."""

    def __init__(self, host=None, port=0):
        self._store = {}          # key -> np.ndarray (authoritative)
        self._updater = None      # (key:int, grad, weight) -> None, in place
        self._beats = {}          # worker rank -> last heartbeat time
        self._dedup = {}          # client id -> highest applied req id
        #                           (mutating RPCs carry monotonic ids; a
        #                           client retries strictly in order, so a
        #                           highwater mark is a complete dedup
        #                           table — graftarmor idempotence)
        self._lock = threading.Lock()
        if host is None:
            host = _default_bind_host()
        self._srv = socket.create_server((host, port))
        # advertise a ROUTABLE address (multi-host workers must reach it;
        # loopback would only ever work same-machine).  When bound to
        # loopback the advertised address must be loopback too — the
        # LAN-interface IP would route to a closed port.
        loopback = host in ("127.0.0.1", "localhost", "::1")
        adv = "127.0.0.1" if loopback else _advertised_host()
        self.address = "%s:%d" % (adv, self._srv.getsockname()[1])
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # -- server loop -------------------------------------------------------
    def _serve(self):
        self._srv.settimeout(0.2)
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    self._dispatch(conn, msg)
                except (ConnectionError, EOFError, OSError):
                    raise
                except Exception as exc:   # server-side failure: REPLY,
                    # keep the connection alive (a dead handler would
                    # hang the worker in _recv_msg)
                    _send_msg(conn, {"ok": False, "error": repr(exc)})
        except (ConnectionError, EOFError, OSError):
            return

    def _dispatch(self, conn, msg):
        cmd = msg["cmd"]
        client, req = msg.get("client"), msg.get("req")
        if client is not None and req is not None:
            # a retried mutating RPC after an ambiguous disconnect (the
            # reply was lost AFTER the server applied it) must not apply
            # twice — acknowledge and drop anything at or below the
            # client's applied highwater
            with self._lock:
                if req <= self._dedup.get(client, 0):
                    _send_msg(conn, {"ok": True, "dedup": True})
                    return
        if cmd == "init":
            with self._lock:
                # first pushed value defines the key
                # (kvstore_dist.h Init semantics)
                for k, v in msg["kv"].items():
                    self._store.setdefault(k, np.array(v))
                self._mark_locked(client, req)
            _send_msg(conn, {"ok": True})
        elif cmd == "push":
            with self._lock:
                for k, g in msg["kv"].items():
                    if self._updater is not None:
                        # async: apply IMMEDIATELY
                        # (kvstore_dist_server.h:306-314).  The
                        # updater speaks NDArray; pin its ops to
                        # the host CPU backend so the server
                        # thread never contends for the
                        # accelerator transport
                        from ..ndarray import NDArray, array
                        from ..context import cpu
                        with cpu(0):
                            w_nd = array(self._store[k])
                            g_nd = array(np.asarray(g))
                            self._updater(self._int_key(k),
                                          g_nd, w_nd)
                            self._store[k] = np.asarray(
                                w_nd.asnumpy())
                    else:
                        w = self._store[k]
                        w += np.asarray(g).astype(w.dtype)
                self._mark_locked(client, req)
            _send_msg(conn, {"ok": True})
        elif cmd == "pull":
            with self._lock:
                out = {k: self._store[k].copy() for k in msg["keys"]}
            _send_msg(conn, {"ok": True, "kv": out})
        elif cmd == "pull_rows":
            # ship ONLY the requested rows (kvstore_dist_server.h:223) —
            # the async row_sparse_pull path must not move whole matrices
            with self._lock:
                rows = {k: self._store[k][np.asarray(ids, np.int64)]
                        for k, ids in msg["kv"].items()}
            _send_msg(conn, {"ok": True, "kv": rows})
        elif cmd == "stat":
            # shape/dtype metadata for the keys this server holds (used
            # by late-joining clients to discover big-array placement)
            with self._lock:
                meta = {k: (tuple(self._store[k].shape),
                            str(self._store[k].dtype))
                        for k in msg["keys"] if k in self._store}
            _send_msg(conn, {"ok": True, "meta": meta})
        elif cmd == "heartbeat":
            with self._lock:
                self._beats[msg["rank"]] = time.monotonic()
            _send_msg(conn, {"ok": True})
        elif cmd == "dead_nodes":
            window = float(msg.get("window", 5.0))
            now = time.monotonic()
            with self._lock:
                dead = [r for r, t in self._beats.items()
                        if now - t > window]
            _send_msg(conn, {"ok": True, "dead": sorted(dead)})
        elif cmd == "set_optimizer":
            # the reference pickles the optimizer to servers
            # (kvstore.py _send_command_to_servers / kController).
            # First writer wins: a late rank's (identical)
            # set_optimizer must NOT wipe accumulated
            # momentum/Adam state
            with self._lock:
                if self._updater is None:
                    from .. import optimizer as opt
                    optimizer = pickle.loads(msg["optimizer"])
                    self._updater = opt.get_updater(optimizer)
                self._mark_locked(client, req)
            _send_msg(conn, {"ok": True})
        elif cmd == "stop":
            _send_msg(conn, {"ok": True})
            self.shutdown()
        else:
            _send_msg(conn, {"ok": False,
                             "error": "unknown cmd %r" % cmd})


    def _mark_locked(self, client, req):
        """Advance one client's applied-request highwater (caller holds
        ``self._lock``).  The client allocates ids monotonically and
        retries in submission order, so max() is exact."""
        if client is not None and req is not None:
            if req > self._dedup.get(client, 0):
                self._dedup[client] = req

    @staticmethod
    def _int_key(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return abs(hash(k)) % (1 << 31)

    def shutdown(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


class PSClient(object):
    """One worker's connection to the parameter service.

    Self-healing (graftarmor): every call runs under a per-call socket
    timeout and a bounded retry loop — timeout/disconnect closes the
    socket (a late reply on the framed stream would pair with the WRONG
    request, so the stream is never reused after a timeout), reconnects,
    backs off exponentially with jitter, and resends.  Mutating commands
    (push/init/set_optimizer) carry a monotonic ``(client, req)`` id so
    a retry after an ambiguous disconnect — reply lost AFTER the server
    applied the mutation — is deduplicated server-side instead of
    double-applied.  Exhausting the budget raises
    :class:`~..armor.errors.PSUnavailableError`.
    """

    # commands whose retry must be idempotent (the dedup table covers
    # exactly these; reads are naturally safe to repeat)
    _MUTATING = frozenset(("push", "init", "set_optimizer"))

    def __init__(self, address):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._client_id = os.urandom(8).hex()
        self._req_id = 0
        self._sock = None
        self._closed = False
        self._lock = threading.Lock()
        self._connect()          # fail loudly at construction, like before

    def _connect(self):
        timeout = rpc_timeout()
        sock = socket.create_connection(self._addr, timeout=timeout)
        sock.settimeout(timeout)
        self._sock = sock

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, msg, retries=None):
        from ..armor import faults as _faults
        from ..armor.errors import FaultInjectedError, PSUnavailableError
        cmd = msg["cmd"]
        with self._lock:
            if self._closed:
                raise PSUnavailableError(cmd, 0, last_error="client closed")
            if cmd in self._MUTATING:
                self._req_id += 1
                msg = dict(msg, client=self._client_id, req=self._req_id)
            budget = rpc_retries() if retries is None else int(retries)
            attempts = budget + 1
            backoff = rpc_backoff_ms() / 1000.0
            last = None
            resp = None
            for attempt in range(attempts):
                try:
                    if self._sock is None:
                        self._connect()
                        if attempt > 0:
                            from ..telemetry import metrics as _tmetrics
                            _tmetrics.rpc_reconnect()
                    act = _faults.fault_point("ps.send", cmd=cmd)
                    if act == "disconnect":
                        self._drop_sock()
                        raise ConnectionError("injected disconnect")
                    if act != "drop":
                        _send_msg(self._sock, msg)
                    ract = _faults.fault_point("ps.recv", cmd=cmd)
                    if ract == "disconnect":
                        self._drop_sock()
                        raise ConnectionError("injected disconnect")
                    if act == "drop" or ract == "drop":
                        # a swallowed request or reply looks like a
                        # silent network drop: the reply never comes
                        raise socket.timeout("injected drop")
                    resp = _recv_msg(self._sock)
                    break
                except (socket.timeout, TimeoutError, ConnectionError,
                        EOFError, OSError, FaultInjectedError) as exc:
                    last = exc
                    self._drop_sock()   # stream desynced: never reuse
                    if attempt + 1 >= attempts:
                        from ..telemetry import blackbox as _blackbox
                        from ..telemetry import metrics as _tmetrics
                        _tmetrics.rpc_gave_up(cmd)
                        _blackbox.record("rpc_gave_up", cmd=cmd,
                                         attempts=attempts,
                                         error=repr(exc))
                        raise PSUnavailableError(
                            cmd, attempts, last_error=exc) from exc
                    from ..telemetry import blackbox as _blackbox
                    from ..telemetry import metrics as _tmetrics
                    _tmetrics.rpc_retry(cmd)
                    _blackbox.record("rpc_retry", cmd=cmd,
                                     attempt=attempt + 1,
                                     error=repr(exc))
                    sleep = min(backoff * (2 ** attempt), 2.0)
                    if sleep > 0:
                        time.sleep(sleep * (0.5 + random.random()))
        if not resp.get("ok"):
            raise RuntimeError("parameter server: %s"
                               % resp.get("error", "unknown failure"))
        return resp

    def init(self, kv):
        self._call({"cmd": "init", "kv": kv})

    def push(self, kv):
        self._call({"cmd": "push", "kv": kv})

    def pull(self, keys):
        return self._call({"cmd": "pull", "keys": list(keys)})["kv"]

    def pull_rows(self, kv):
        """{key: row_ids} -> {key: rows} — only the requested rows move."""
        return self._call({"cmd": "pull_rows", "kv": kv})["kv"]

    def stat(self, keys):
        """{key: (shape, dtype)} for the keys this server holds."""
        return self._call({"cmd": "stat", "keys": list(keys)})["meta"]

    def set_optimizer(self, optimizer):
        self._call({"cmd": "set_optimizer",
                    "optimizer": pickle.dumps(optimizer)})

    def heartbeat(self, rank):
        # liveness probes must not mask death by retrying: one attempt
        self._call({"cmd": "heartbeat", "rank": int(rank)}, retries=0)

    def dead_nodes(self, window=5.0):
        return self._call({"cmd": "dead_nodes", "window": window},
                          retries=0)["dead"]

    def close(self):
        with self._lock:
            self._closed = True      # no teardown-time reconnect storms
            self._drop_sock()


class ServerGroup(object):
    """N server threads in one process — the server-group role of ps-lite.
    Keys hash-shard across members; big arrays row-slice across ALL of
    them (GroupClient does the placement)."""

    def __init__(self, num_servers=1):
        self.servers = [ParameterServer() for _ in range(max(1, num_servers))]
        self.address = ",".join(s.address for s in self.servers)

    def shutdown(self):
        for s in self.servers:
            s.shutdown()


class GroupClient(object):
    """One worker's connections to a ServerGroup.

    Placement: key k lives on server ``crc32(k) % N`` unless its value
    exceeds BIGARRAY_BOUND elements, in which case its rows are sliced
    into N contiguous blocks, block i on server i under subkey ``k@i``
    (the reference's MXNET_KVSTORE_BIGARRAY_BOUND sharding).  A
    background thread heartbeats every server so the group can report
    dead workers.
    """

    def __init__(self, address, rank=None):
        self._clients = [PSClient(a) for a in address.split(",")]
        self._n = len(self._clients)
        self._big = {}            # key -> row-block boundaries (list)
        self._small = set()       # keys known to live whole on one shard
        self._rank = rank
        self._hb_stop = threading.Event()
        if rank is not None:
            t = threading.Thread(target=self._beat_loop, daemon=True)
            t.start()

    # -- placement ---------------------------------------------------------
    def _shard_of(self, key):
        return zlib.crc32(str(key).encode()) % self._n

    def _blocks(self, key, nrows):
        cuts = np.linspace(0, nrows, self._n + 1).astype(int)
        self._big[key] = cuts
        return cuts

    def _is_big(self, v):
        return self._n > 1 and v.ndim >= 1 and v.size > BIGARRAY_BOUND()

    def _discover(self, key):
        """Resolve placement for a key this client never init/pushed (a
        late-joining or restarted worker): ask the hash shard first, then
        probe every server for the key's row blocks and rebuild the cut
        table from the block shapes.  Results cache both ways, so the
        hot pull path pays the stat round-trip once per key."""
        if key in self._big:
            return True
        if key in self._small:
            return False
        if self._clients[self._shard_of(key)].stat([key]).get(key):
            self._small.add(key)
            return False            # whole key on its hash shard: small
        nrows = [0] * self._n
        found = False
        for i, c in enumerate(self._clients):
            meta = c.stat(["%s@%d" % (key, i)]).get("%s@%d" % (key, i))
            if meta:
                nrows[i] = meta[0][0]
                found = True
        if not found:
            raise KeyError("parameter %r unknown to the server group" % key)
        self._big[key] = np.concatenate([[0], np.cumsum(nrows)])
        return True

    def _beat_loop(self):
        # first beat IMMEDIATELY: membership must register before a fast
        # exit, or a worker that dies young is never counted dead
        while True:
            alive = 0
            for c in self._clients:
                # per-server failure isolation: one broken connection must
                # not silence heartbeats to the healthy members (which
                # would count this live worker dead)
                try:
                    c.heartbeat(self._rank)
                    alive += 1
                except Exception:
                    continue
            if alive == 0:
                return            # whole group gone: nothing to report to
            if self._hb_stop.wait(1.0):
                return

    # -- api (same surface as PSClient) ------------------------------------
    def init(self, kv):
        per = [dict() for _ in range(self._n)]
        for k, v in kv.items():
            v = np.asarray(v)
            if self._is_big(v):
                cuts = self._blocks(k, v.shape[0])
                for i in range(self._n):
                    per[i]["%s@%d" % (k, i)] = v[cuts[i]:cuts[i + 1]]
            else:
                self._small.add(k)
                per[self._shard_of(k)][k] = v
        for c, kvs in zip(self._clients, per):
            if kvs:
                c.init(kvs)

    def push(self, kv):
        per = [dict() for _ in range(self._n)]
        for k, v in kv.items():
            v = np.asarray(v)
            if k in self._big or (k not in self._small and self._is_big(v)):
                cuts = self._big.get(k)
                if cuts is None:
                    cuts = self._blocks(k, v.shape[0])
                for i in range(self._n):
                    per[i]["%s@%d" % (k, i)] = v[cuts[i]:cuts[i + 1]]
            else:
                self._small.add(k)
                per[self._shard_of(k)][k] = v
        for c, kvs in zip(self._clients, per):
            if kvs:
                c.push(kvs)

    def pull(self, keys):
        if self._n > 1:
            for k in keys:
                self._discover(k)
        per = [list() for _ in range(self._n)]
        for k in keys:
            if k in self._big:
                for i in range(self._n):
                    per[i].append("%s@%d" % (k, i))
            else:
                per[self._shard_of(k)].append(k)
        got = {}
        for c, ks in zip(self._clients, per):
            if ks:
                got.update(c.pull(ks))
        out = {}
        for k in keys:
            if k in self._big:
                out[k] = np.concatenate(
                    [got["%s@%d" % (k, i)] for i in range(self._n)], axis=0)
            else:
                out[k] = got[k]
        return out

    def pull_rows(self, kv):
        """{key: row_ids} -> {key: rows}: only requested rows cross the
        wire, routed to the owning row-block for sharded arrays."""
        out = {}
        for k, ids in kv.items():
            ids = np.asarray(ids, np.int64)
            if self._n > 1:
                self._discover(k)
            if ids.size == 0:
                # metadata only — never move the table for an empty pull
                if k in self._big:
                    meta = self._clients[0].stat([k + "@0"])[k + "@0"]
                else:
                    meta = self._clients[self._shard_of(k)].stat([k])[k]
                out[k] = np.empty((0,) + tuple(meta[0][1:]),
                                  np.dtype(meta[1]))
            elif k in self._big:
                cuts = self._big[k]
                parts = np.empty((len(ids),), object)
                for i in range(self._n):
                    sel = (ids >= cuts[i]) & (ids < cuts[i + 1])
                    if not sel.any():
                        continue
                    rows = self._clients[i].pull_rows(
                        {"%s@%d" % (k, i): ids[sel] - cuts[i]})
                    vals = rows["%s@%d" % (k, i)]
                    for j, pos in enumerate(np.nonzero(sel)[0]):
                        parts[pos] = vals[j]
                out[k] = np.stack(list(parts))
            else:
                out[k] = self._clients[self._shard_of(k)].pull_rows(
                    {k: ids})[k]
        return out

    def set_optimizer(self, optimizer):
        for c in self._clients:
            c.set_optimizer(optimizer)

    def dead_nodes(self, window=5.0):
        dead = set()
        for c in self._clients:
            dead.update(c.dead_nodes(window))
        return sorted(dead)

    def close(self):
        self._hb_stop.set()
        for c in self._clients:
            c.close()


# -- address rendezvous through the jax coordination service ---------------

_ADDR_KEY = "mxtpu/ps_address"


def _coord_client():
    from jax._src import distributed
    state = distributed.global_state
    return getattr(state, "client", None)


def publish_address(address, idx=0):
    client = _coord_client()
    if client is not None:
        client.key_value_set("%s/%d" % (_ADDR_KEY, idx), address)


def lookup_address(idx=0, timeout_ms=60000):
    import os
    env = os.environ.get("MX_PS_ADDR")
    if env:
        return env
    client = _coord_client()
    if client is None:
        raise RuntimeError(
            "dist_async needs the jax.distributed coordination service "
            "(run under tools/launch.py) or MX_PS_ADDR set")
    return client.blocking_key_value_get("%s/%d" % (_ADDR_KEY, idx),
                                         timeout_ms)
