"""Host-side parameter service: real ``dist_async`` semantics.

The reference's async mode is a ps-lite server applying each worker's
push the moment it arrives (src/kvstore/kvstore_dist_server.h:113-314:
``DataHandleEx`` dispatch, async branch at :306-314, the pickled
optimizer executed server-side via the kController command channel).
XLA's synchronous SPMD model cannot express that — so, exactly as the
reference does, the asynchronous state lives on a HOST service:

* rank 0 runs a ``ParameterServer`` thread — a pickle-framed TCP
  server holding the authoritative f32 weights and applying the
  (pickled, ``set_optimizer``-shipped) optimizer to every arriving
  gradient immediately: no barrier, no merge window, pure async.
* every worker's ``DistKVStore("dist_async")`` connects as a client:
  ``push`` ships the gradient and returns, ``pull`` fetches whatever
  the weights are *right now* — staleness included, which is the whole
  point of async SGD.
* the server address travels through the jax.distributed coordination
  service's key-value store (the Postoffice/scheduler's successor), so
  launch topology stays tools/launch.py with zero extra flags.

This is a prototype-grade transport (one TCP connection per worker,
pickled frames) standing in for ps-lite's ZMQ — the semantics
(immediate-apply, server-side updater, update_on_kvstore) are the
reference's, the wire is deliberately simple.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading

import numpy as np

__all__ = ["ParameterServer", "PSClient", "publish_address",
           "lookup_address"]

_LEN = struct.Struct("<Q")


def _advertised_host():
    import os
    env = os.environ.get("MX_PS_HOST")
    if env:
        return env
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, n))


class ParameterServer(object):
    """The server role (runs as a daemon thread inside rank 0's process —
    the reference would run it in dedicated server processes; one thread
    suffices for the single-server topology)."""

    def __init__(self, host="0.0.0.0", port=0):
        self._store = {}          # key -> np.ndarray (authoritative)
        self._updater = None      # (key:int, grad, weight) -> None, in place
        self._lock = threading.Lock()
        self._srv = socket.create_server((host, port))
        # advertise a ROUTABLE address (multi-host workers must reach it;
        # loopback would only ever work same-machine)
        adv = _advertised_host()
        self.address = "%s:%d" % (adv, self._srv.getsockname()[1])
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # -- server loop -------------------------------------------------------
    def _serve(self):
        self._srv.settimeout(0.2)
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            while True:
                msg = _recv_msg(conn)
                try:
                    self._dispatch(conn, msg)
                except (ConnectionError, EOFError, OSError):
                    raise
                except Exception as exc:   # server-side failure: REPLY,
                    # keep the connection alive (a dead handler would
                    # hang the worker in _recv_msg)
                    _send_msg(conn, {"ok": False, "error": repr(exc)})
        except (ConnectionError, EOFError, OSError):
            return

    def _dispatch(self, conn, msg):
        cmd = msg["cmd"]
        if cmd == "init":
            with self._lock:
                # first pushed value defines the key
                # (kvstore_dist.h Init semantics)
                for k, v in msg["kv"].items():
                    self._store.setdefault(k, np.array(v))
            _send_msg(conn, {"ok": True})
        elif cmd == "push":
            with self._lock:
                for k, g in msg["kv"].items():
                    if self._updater is not None:
                        # async: apply IMMEDIATELY
                        # (kvstore_dist_server.h:306-314).  The
                        # updater speaks NDArray; pin its ops to
                        # the host CPU backend so the server
                        # thread never contends for the
                        # accelerator transport
                        from ..ndarray import NDArray, array
                        from ..context import cpu
                        with cpu(0):
                            w_nd = array(self._store[k])
                            g_nd = array(np.asarray(g))
                            self._updater(self._int_key(k),
                                          g_nd, w_nd)
                            self._store[k] = np.asarray(
                                w_nd.asnumpy())
                    else:
                        w = self._store[k]
                        w += np.asarray(g).astype(w.dtype)
            _send_msg(conn, {"ok": True})
        elif cmd == "pull":
            with self._lock:
                out = {k: self._store[k].copy() for k in msg["keys"]}
            _send_msg(conn, {"ok": True, "kv": out})
        elif cmd == "set_optimizer":
            # the reference pickles the optimizer to servers
            # (kvstore.py _send_command_to_servers / kController).
            # First writer wins: a late rank's (identical)
            # set_optimizer must NOT wipe accumulated
            # momentum/Adam state
            with self._lock:
                if self._updater is None:
                    from .. import optimizer as opt
                    optimizer = pickle.loads(msg["optimizer"])
                    self._updater = opt.get_updater(optimizer)
            _send_msg(conn, {"ok": True})
        elif cmd == "stop":
            _send_msg(conn, {"ok": True})
            self.shutdown()
        else:
            _send_msg(conn, {"ok": False,
                             "error": "unknown cmd %r" % cmd})


    @staticmethod
    def _int_key(k):
        try:
            return int(k)
        except (TypeError, ValueError):
            return abs(hash(k)) % (1 << 31)

    def shutdown(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


class PSClient(object):
    """One worker's connection to the parameter service."""

    def __init__(self, address):
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=60)
        self._lock = threading.Lock()

    def _call(self, msg):
        with self._lock:
            _send_msg(self._sock, msg)
            resp = _recv_msg(self._sock)
        if not resp.get("ok"):
            raise RuntimeError("parameter server: %s"
                               % resp.get("error", "unknown failure"))
        return resp

    def init(self, kv):
        self._call({"cmd": "init", "kv": kv})

    def push(self, kv):
        self._call({"cmd": "push", "kv": kv})

    def pull(self, keys):
        return self._call({"cmd": "pull", "keys": list(keys)})["kv"]

    def set_optimizer(self, optimizer):
        self._call({"cmd": "set_optimizer",
                    "optimizer": pickle.dumps(optimizer)})

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


# -- address rendezvous through the jax coordination service ---------------

_ADDR_KEY = "mxtpu/ps_address"


def _coord_client():
    from jax._src import distributed
    state = distributed.global_state
    return getattr(state, "client", None)


def publish_address(address, idx=0):
    client = _coord_client()
    if client is not None:
        client.key_value_set("%s/%d" % (_ADDR_KEY, idx), address)


def lookup_address(idx=0, timeout_ms=60000):
    import os
    env = os.environ.get("MX_PS_ADDR")
    if env:
        return env
    client = _coord_client()
    if client is None:
        raise RuntimeError(
            "dist_async needs the jax.distributed coordination service "
            "(run under tools/launch.py) or MX_PS_ADDR set")
    return client.blocking_key_value_get("%s/%d" % (_ADDR_KEY, idx),
                                         timeout_ms)
