"""Wire-level 2-bit gradient packing.

The reference compresses gradients to 2 bits per value and packs 16
values into each 32-bit word before they touch the network
(src/kvstore/gradient_compression.h:37-132, quantize_2bit in the .cu
twin: code 0 = zero, 1 = +threshold, 2 = -threshold).  Round 2 carried
the *algebra* (quantize + residual) but shipped full f32 words — zero
bandwidth saved.  This module supplies the missing wire format as XLA
kernels:

* ``encode_2bit``     — {-t, 0, +t} values → packed uint32 (16 lanes/word)
* ``decode_2bit_sum`` — (num_workers, nwords) packed → f32 sum over workers

and the collective that moves ONLY packed words between processes:
``allgather_packed`` is a jitted identity whose input is sharded over the
one-device-per-process "worker" mesh and whose output is replicated — XLA
lowers exactly one all-gather of the uint32 payload (1/16 the bytes of
the f32 buffer).  Dequantize + sum then run as local, comm-free XLA ops
on every worker — each worker plays the reference server's dequant role
(kvstore_dist_server.h:389 DataHandleCompressed), collapsed into the
allreduce topology the TPU wire actually has.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["encode_2bit", "decode_2bit", "decode_2bit_sum",
           "allgather_packed", "packed_nbytes", "allreduce_packed_sum",
           "wire_bytes_per_worker"]

_LANES = 16  # 2-bit codes per uint32 word (gradient_compression.h:44)


def packed_words(n):
    return (n + _LANES - 1) // _LANES


def packed_nbytes(n):
    """Bytes on the wire for n values — the 1/16-of-f32 contract."""
    return 4 * packed_words(n)


@jax.jit
def _encode(q, half_t):
    n = q.shape[0]
    nw = packed_words(n)
    codes = jnp.where(q > half_t, jnp.uint32(1),
                      jnp.where(q < -half_t, jnp.uint32(2), jnp.uint32(0)))
    codes = jnp.pad(codes, (0, nw * _LANES - n))
    shifts = (jnp.arange(_LANES, dtype=jnp.uint32) * 2)[None, :]
    # disjoint bit fields: the sum IS the bitwise-or of the shifted lanes
    return jnp.sum(codes.reshape(nw, _LANES) << shifts, axis=1,
                   dtype=jnp.uint32)


def encode_2bit(q, threshold):
    """Pack a flat f32 buffer of quantized values {-t, 0, +t} into uint32
    words, 16 two-bit codes per word."""
    return _encode(q.ravel(), jnp.float32(threshold / 2.0))


def _lanes(words):
    shifts = (jnp.arange(_LANES, dtype=jnp.uint32) * 2)
    return (words[..., None] >> shifts[None, :]) & jnp.uint32(3)


@jax.jit
def _decode(words, t):
    c = _lanes(words)
    vals = jnp.where(c == 1, t, jnp.where(c == 2, -t, jnp.float32(0.0)))
    return vals.reshape(words.shape[:-1] + (-1,))


def decode_2bit(words, threshold, n):
    """Unpack one worker's words back to the quantized f32 values."""
    return _decode(words, jnp.float32(threshold))[..., :n]


@jax.jit
def _decode_sum(words_all, t):
    c = _lanes(words_all)  # (W, nw, LANES)
    vals = jnp.where(c == 1, t, jnp.where(c == 2, -t, jnp.float32(0.0)))
    return jnp.sum(vals, axis=0).reshape(-1)


def decode_2bit_sum(words_all, threshold, n):
    """(num_workers, nwords) packed → f32[n] sum of all workers' values.
    Pure local compute (the per-worker 'server-side' dequant+merge)."""
    return _decode_sum(words_all, jnp.float32(threshold))[:n]


def _assemble_worker_global(local, mesh):
    """Build the (W, ...) global array whose row for THIS process is
    ``local``, sharded over the mesh's 'worker' axis (one device per
    process — the kvstore wire topology)."""
    me = jax.process_index()
    my_dev = next(d for d in mesh.devices.flat if d.process_index == me)
    piece = jax.device_put(local[None], my_dev)
    return jax.make_array_from_single_device_arrays(
        (mesh.shape["worker"],) + tuple(local.shape),
        NamedSharding(mesh, P("worker")), [piece])


def _sum_code_dtype(W):
    # shard sums are exact integer multiples of t in [-W, W]
    return jnp.int8 if W <= 127 else jnp.int16


def wire_bytes_per_worker(n, W):
    """(compressed, dense) bytes a worker RECEIVES for an n-value reduce.

    Compressed = packed all-to-all (2-bit codes) + int8 sum all-gather —
    both W-independent (~n/4 + n); dense = ring all-reduce of f32
    (~8n).  The old allgather-of-codes wire was (W-1)·n/4 — worse than
    dense past W≈33 and O(W·n) decode; this one wins at every W.
    """
    nw = packed_words(n)
    k = -(-nw // W)
    code_bytes = 1 if W <= 127 else 2
    compressed = (W - 1) * k * 4 + (W - 1) * k * _LANES * code_bytes
    dense = 2 * 4 * n * (W - 1) // W
    return compressed, dense


_rs_jit_cache = {}


def _rs_jitted(mesh, W, k, sum_dtype):
    """Jit: (W, W·k) packed words sharded over 'worker' → replicated
    (W·k·16,) integer sum codes.  Per shard-map block: all_to_all ships
    each destination its k-word slice from every worker (the compressed
    reduce-scatter), the block decodes ONLY its shard (O(n/W) lanes) and
    sums over workers; the replicated out_sharding makes GSPMD all-gather
    the narrow integer codes, not f32."""
    key = (mesh, W, k, sum_dtype)
    fn = _rs_jit_cache.get(key)
    if fn is None:
        from .._jax_compat import shard_map
        from jax import lax

        def body(block):                       # (1, W*k) uint32
            shards = block[0].reshape(W, k)    # row j → destination j
            recv = lax.all_to_all(shards, "worker", split_axis=0,
                                  concat_axis=0, tiled=False)
            recv = recv.reshape(W, k)          # row j → worker j's slice
            c = _lanes(recv)                   # (W, k, 16)
            vals = jnp.where(c == 1, 1, jnp.where(c == 2, -1, 0))
            return vals.sum(axis=0, dtype=jnp.int32).astype(
                sum_dtype).reshape(1, -1)      # (1, k*16)

        def run(garr):
            out = shard_map(body, mesh=mesh,
                            in_specs=P("worker", None),
                            out_specs=P("worker", None),
                            check_vma=False)(garr)
            return out.reshape(-1)

        fn = jax.jit(run, out_shardings=NamedSharding(mesh, P()))
        _rs_jit_cache[key] = fn
    return fn


def allreduce_packed_sum(words, threshold, n, mesh):
    """Scale-correct compressed all-reduce: this process's packed words in,
    replicated f32[n] sum of every worker's values out.

    Wire cost per worker is W-independent (see wire_bytes_per_worker);
    decode compute is O(n) total per worker (each decodes only its own
    shard of every peer).  The int8 re-encode of the shard sums is EXACT:
    sums are integer multiples of the threshold with |multiple| ≤ W
    (int16 beyond 127 workers).  ref: gradient_compression.h:37-132 wire
    format; kvstore_dist_server.h:389 server-side dequant role, here
    distributed across the reduce-scatter shards."""
    W = mesh.shape["worker"]
    nw = words.shape[0]
    k = -(-nw // W)
    wordsp = jnp.pad(words, (0, k * W - nw))
    sum_dtype = _sum_code_dtype(W)
    fn = _rs_jitted(mesh, W, k, sum_dtype)
    garr = _assemble_worker_global(wordsp, mesh)
    codes = jnp.asarray(fn(garr).addressable_data(0))
    return codes[:n].astype(jnp.float32) * jnp.float32(threshold)


_gather_jit_cache = {}


def allgather_packed(words, mesh):
    """Ship THIS process's packed words to every process; returns the
    replicated (num_workers, nwords) uint32 array.  The only bytes that
    cross the wire are the packed codes."""
    _gather_jit = _gather_jit_cache.get(mesh)
    if _gather_jit is None:
        _gather_jit = jax.jit(lambda a: a,
                              out_shardings=NamedSharding(mesh, P()))
        _gather_jit_cache[mesh] = _gather_jit
    out = _gather_jit(_assemble_worker_global(words, mesh))
    return jnp.asarray(out.addressable_data(0))
