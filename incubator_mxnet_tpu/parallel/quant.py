"""graftzero wire: block-scaled quantized bucket allreduce.

EQuARX-style (arXiv:2506.17615) block-scaled quantization for the
bucketed gradient wire (graftfuse/graftlap/graftduplex/graftstep): each
bucket's flat gradient is cut into blocks of ``GRAFT_QUANT_BLOCK``
elements (default 256), every block gets one f32 scale, and the values
ride as narrow integer codes:

* ``int8`` — codes in [-127, 127], scale = max|block| / 127.  Wire is
  ~n + n/block·4 bytes vs 4n dense f32 (≥3.5x at the default block).
* ``2bit`` — codes in {-1, 0, +1} (packed 16 per uint32 word, the
  gradient_compression.h wire format), scale = max|block|, threshold at
  scale/2.  Wire is ~n/4 + n/block·4 bytes.

The payload of one bucket is (codes, scales) — one packed code buffer
plus one scale vector — and it crosses the wire as ONE collective
program: on the multi-worker mesh an all-to-all ships every worker its
contiguous shard of blocks (codes AND scales), the shard is dequantized
per source and summed in f32, the shard SUM is re-quantized with fresh
scales, and the replicated output all-gathers only the narrow codes +
scales (the EQuARX reduce-scatter + all-gather — no f32 collective
anywhere).  Single-worker stores reduce nothing; the payload round-trips
encode→decode locally so the algebra (and the byte accounting) is
identical everywhere.

Quantization error is recycled through ERROR FEEDBACK: the residual
``acc - dequant(quant(acc))`` of every bucket is kept in the Updater
state store (string-keyed beside the per-param optimizer state), so
``save_states``/``load_states`` and graftarmor checkpoint/resume carry
it for free and quantized-SGD converges to the float fixed point (the
classic EF-SGD telescoping argument; see the selftest).

Tolerance contract (documented in docs/observability.md): for one
encode→decode round trip the per-element error is bounded by
``max|block| / 254`` for int8 (half a code step) and ``max|block| / 2``
for 2bit; error feedback keeps the ACCUMULATED error of a training
trajectory bounded by one step's quantization error instead of growing
with step count.

``GRAFT_SHARD_OPTIMIZER=1`` (ZeRO-1) helpers live here too: the
contiguous bucket→owner assignment used by the Trainer's sharded fused
update.
"""
from __future__ import annotations

import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["resolve_mode", "resolve_block", "zero_enabled", "MODES",
           "wire_nbytes", "n_blocks", "encode", "decode",
           "reduce_payload_sum", "shard_owners", "BucketQuantizer",
           "QuantReduceHandle", "selftest"]

MODES = ("int8", "2bit")
_LANES = 16              # 2-bit codes per uint32 word
_DEFAULT_BLOCK = 256


def resolve_mode(override=None):
    """The active quant mode: ``GRAFT_QUANT_REDUCE`` ∈ {int8, 2bit}
    enables; ``0``/``off``/unset disables.  ``override`` is the
    deprecated ``set_gradient_compression("2bit")`` routing — the env
    var always wins, so ``GRAFT_QUANT_REDUCE=0`` stays the bit-identical
    escape hatch even with compression params set."""
    raw = os.environ.get("GRAFT_QUANT_REDUCE", "").strip().lower()
    if raw in MODES:
        return raw
    if raw in ("0", "off", "false", "no"):
        return None
    return override if override in MODES else None


def resolve_block():
    """GRAFT_QUANT_BLOCK elements per scale block (default 256), rounded
    up to a multiple of 16 so 2-bit word packing never straddles a
    block boundary."""
    try:
        b = int(os.environ.get("GRAFT_QUANT_BLOCK", str(_DEFAULT_BLOCK)))
    except ValueError:
        b = _DEFAULT_BLOCK
    b = max(b, _LANES)
    return ((b + _LANES - 1) // _LANES) * _LANES


def zero_enabled():
    """GRAFT_SHARD_OPTIMIZER (default off): ZeRO-1 sharded fused update —
    each rank/ctx owns a contiguous shard of buckets and holds optimizer
    state only for it."""
    return os.environ.get("GRAFT_SHARD_OPTIMIZER", "").strip().lower() \
        in ("1", "on", "true", "yes")


def n_blocks(n, block):
    return -(-int(n) // int(block))


def wire_nbytes(n, mode, block):
    """Bytes of one n-element payload on the wire: packed codes + f32
    scales.  This is what the kvstore byte counters report for a
    quantized reduce (satellite: wire bytes count quantized bytes, not
    the dequantized size)."""
    nb = n_blocks(n, block)
    if mode == "int8":
        return nb * block + 4 * nb
    if mode == "2bit":
        return nb * (block // _LANES) * 4 + 4 * nb
    raise ValueError("unknown quant mode %r" % (mode,))


# -- kernels (jitted, static block so shapes are compile-time) -------------

@partial(jax.jit, static_argnums=(1,))
def _encode_int8(flat, block):
    n = flat.shape[0]
    nb = n_blocks(n, block)
    x = jnp.pad(flat.astype(jnp.float32),
                (0, nb * block - n)).reshape(nb, block)
    scales = jnp.max(jnp.abs(x), axis=1) / jnp.float32(127.0)
    safe = jnp.where(scales > 0, scales, jnp.float32(1.0))
    codes = jnp.clip(jnp.round(x / safe[:, None]), -127, 127) \
        .astype(jnp.int8).reshape(-1)
    return codes, scales


@partial(jax.jit, static_argnums=(2, 3))
def _decode_int8(codes, scales, n, block):
    nb = n_blocks(n, block)
    vals = codes.astype(jnp.float32).reshape(nb, block) * scales[:, None]
    return vals.reshape(-1)[:n]


def _pack_2bit(codes, nb, bw):
    # codes: (nb, block) uint32 in {0,1,2}; disjoint bit fields — the
    # sum IS the bitwise-or of the shifted lanes
    shifts = (jnp.arange(_LANES, dtype=jnp.uint32) * 2)[None, :]
    return jnp.sum(codes.reshape(nb * bw, _LANES) << shifts, axis=1,
                   dtype=jnp.uint32).reshape(nb, bw)


def _unpack_2bit(words):
    shifts = (jnp.arange(_LANES, dtype=jnp.uint32) * 2)
    return (words[..., None] >> shifts) & jnp.uint32(3)


@partial(jax.jit, static_argnums=(1,))
def _encode_2bit(flat, block):
    n = flat.shape[0]
    nb = n_blocks(n, block)
    bw = block // _LANES
    x = jnp.pad(flat.astype(jnp.float32),
                (0, nb * block - n)).reshape(nb, block)
    scales = jnp.max(jnp.abs(x), axis=1)
    half = scales[:, None] / 2
    codes = jnp.where(x > half, jnp.uint32(1),
                      jnp.where(x < -half, jnp.uint32(2), jnp.uint32(0)))
    return _pack_2bit(codes, nb, bw).reshape(-1), scales


@partial(jax.jit, static_argnums=(2, 3))
def _decode_2bit(words, scales, n, block):
    nb = n_blocks(n, block)
    bw = block // _LANES
    c = _unpack_2bit(words.reshape(nb, bw))            # (nb, bw, 16)
    sign = jnp.where(c == 1, 1.0, jnp.where(c == 2, -1.0, 0.0))
    vals = sign.reshape(nb, block) * scales[:, None]
    return vals.reshape(-1)[:n]


def encode(flat, mode, block):
    """flat f32-like 1-D → (codes, scales).  codes is int8[nb·block]
    (int8) or packed uint32[nb·block/16] (2bit); scales is f32[nb]."""
    if mode == "int8":
        return _encode_int8(flat.ravel(), int(block))
    if mode == "2bit":
        return _encode_2bit(flat.ravel(), int(block))
    raise ValueError("unknown quant mode %r" % (mode,))


def decode(codes, scales, n, mode, block):
    """(codes, scales) → f32[n] dequantized values."""
    if mode == "int8":
        return _decode_int8(codes, scales, int(n), int(block))
    if mode == "2bit":
        return _decode_2bit(codes, scales, int(n), int(block))
    raise ValueError("unknown quant mode %r" % (mode,))


# -- the multi-worker payload collective -----------------------------------

_reduce_jit_cache = {}


def _payload_reduce_jitted(mesh, W, kb, block, mode):
    """Jit: this worker's (W·kb)-block payload sharded over 'worker' →
    replicated re-quantized SUM payload.  Per shard-map block: all_to_all
    ships each destination its kb-block slice of codes AND scales from
    every worker (the quantized reduce-scatter), the block dequantizes
    ONLY its shard per source, sums in f32, re-quantizes the shard sum
    with fresh scales, and the replicated out_sharding makes GSPMD
    all-gather the narrow codes + scales — no f32 collective anywhere
    (same lowering discipline as compression._rs_jitted)."""
    key = (mesh, W, kb, block, mode)
    fn = _reduce_jit_cache.get(key)
    if fn is None:
        from .._jax_compat import shard_map
        from jax import lax
        bw = block // _LANES

        def body(codes_blk, scales_blk):
            s = scales_blk[0].reshape(W, kb)
            srecv = lax.all_to_all(s, "worker", split_axis=0,
                                   concat_axis=0, tiled=False)
            if mode == "int8":
                c = codes_blk[0].reshape(W, kb, block)
                crecv = lax.all_to_all(c, "worker", split_axis=0,
                                       concat_axis=0, tiled=False)
                tot = jnp.sum(crecv.astype(jnp.float32)
                              * srecv[..., None], axis=0)   # (kb, block)
                ns = jnp.max(jnp.abs(tot), axis=1) / jnp.float32(127.0)
                safe = jnp.where(ns > 0, ns, jnp.float32(1.0))
                nc = jnp.clip(jnp.round(tot / safe[:, None]), -127, 127) \
                    .astype(jnp.int8).reshape(1, kb * block)
            else:
                w = codes_blk[0].reshape(W, kb, bw)
                wrecv = lax.all_to_all(w, "worker", split_axis=0,
                                       concat_axis=0, tiled=False)
                c = _unpack_2bit(wrecv)                 # (W, kb, bw, 16)
                sign = jnp.where(c == 1, 1.0,
                                 jnp.where(c == 2, -1.0, 0.0))
                vals = sign.reshape(W, kb, block) * srecv[..., None]
                tot = vals.sum(axis=0)                  # (kb, block)
                ns = jnp.max(jnp.abs(tot), axis=1)
                half = ns[:, None] / 2
                qc = jnp.where(tot > half, jnp.uint32(1),
                               jnp.where(tot < -half, jnp.uint32(2),
                                         jnp.uint32(0)))
                nc = _pack_2bit(qc, kb, bw).reshape(1, kb * bw)
            return nc, ns.reshape(1, kb)

        def run(codes_g, scales_g):
            return shard_map(body, mesh=mesh,
                             in_specs=(P("worker", None),
                                       P("worker", None)),
                             out_specs=(P("worker", None),
                                        P("worker", None)),
                             check_vma=False)(codes_g, scales_g)

        fn = jax.jit(run, out_shardings=(NamedSharding(mesh, P()),
                                         NamedSharding(mesh, P())))
        _reduce_jit_cache[key] = fn
    return fn


def reduce_payload_sum(codes, scales, n, mode, block, mesh):
    """Scale-correct quantized all-reduce of one bucket payload: this
    process's (codes, scales) in, the replicated RE-QUANTIZED payload of
    the cross-worker sum out (dequantize with :func:`decode`).  One
    compiled program per (mesh, shape, mode) — the bucket's single
    collective."""
    from .compression import _assemble_worker_global
    W = mesh.shape["worker"]
    nb = n_blocks(n, block)
    kb = -(-nb // W)
    bw = block // _LANES
    per_block = block if mode == "int8" else bw
    codes = jnp.pad(codes.reshape(nb, per_block),
                    ((0, kb * W - nb), (0, 0))).reshape(-1)
    scales = jnp.pad(scales, (0, kb * W - nb))
    fn = _payload_reduce_jitted(mesh, W, kb, block, mode)
    cg = _assemble_worker_global(codes, mesh)
    sg = _assemble_worker_global(scales, mesh)
    oc, os_ = fn(cg, sg)
    oc = jnp.asarray(oc.addressable_data(0))[:nb * per_block]
    os_ = jnp.asarray(os_.addressable_data(0))[:nb]
    return oc, os_


# -- ZeRO-1 shard assignment -----------------------------------------------

def shard_owners(n_buckets, n_shards):
    """Contiguous bucket→owner assignment: bucket k belongs to shard
    ``k * n_shards // n_buckets`` — shards are contiguous runs of the
    plan order and every rank derives the identical map (lockstep)."""
    n_buckets, n_shards = int(n_buckets), max(1, int(n_shards))
    return tuple(min(k * n_shards // max(n_buckets, 1), n_shards - 1)
                 for k in range(n_buckets))


# -- error-feedback bucket quantizer ---------------------------------------

_RES_PREFIX = "__quant_ef__"


def residual_key(indices, dtype):
    """The Updater-store key one bucket's error-feedback residual lives
    under — string-namespaced beside the int per-param optimizer state,
    so ``get_states``/``set_states`` (and armor snapshots) carry it."""
    return "%s/%s:%s" % (_RES_PREFIX, np.dtype(dtype).name,
                         ",".join(str(i) for i in indices))


def is_residual_key(key):
    return isinstance(key, str) and key.startswith(_RES_PREFIX)


class QuantReduceHandle(object):
    """Wraps the in-flight payload reduce of one bucket: ``wait()``
    settles the wire handle, dequantizes the reduced payload INTO the
    bucket's flat buffer and returns ``[flat]`` — drop-in for the
    :class:`~..kvstore.ReduceHandle` the overlap scheduler and the
    Trainer's wait loop already speak."""

    __slots__ = ("_inner", "_flat", "_n", "_mode", "_block", "_decoded")

    def __init__(self, inner, flat, n, mode, block):
        self._inner = inner
        self._flat = flat
        self._n = int(n)
        self._mode = mode
        self._block = int(block)
        self._decoded = False

    @property
    def issued_at(self):
        return self._inner.issued_at

    @property
    def label(self):
        return self._inner.label

    @property
    def done(self):
        return self._inner.done

    @property
    def blocked_s(self):
        return self._inner.blocked_s

    @property
    def inflight_s(self):
        return self._inner.inflight_s

    def wait(self):
        vals = self._inner.wait()
        if not self._decoded:
            self._decoded = True
            codes, scales = vals[0]._read(), vals[1]._read()
            out = decode(codes, scales, self._n, self._mode, self._block)
            self._flat._write(out.astype(self._flat.dtype))
        return [self._flat]

    def abandon(self):
        self._inner.abandon()


class BucketQuantizer(object):
    """Quantized replacement for one step's bucket reduces.

    ``store_fn`` returns the Updater whose ``states`` dict owns the
    error-feedback residuals (the Trainer's ``_updaters[0]`` on the
    fused path, the store-side updater on the duplex path) — keeping
    them there is what makes ``save_states`` / armor checkpoints carry
    them without any extra plumbing."""

    def __init__(self, mode, block, store_fn):
        self.mode = mode
        self.block = int(block)
        self._store_fn = store_fn

    # -- residual store ----------------------------------------------------
    def _residual(self, key, like):
        states = self._store_fn().states
        r = states.get(key)
        if r is None:
            return jnp.zeros_like(like)
        if not isinstance(r, jnp.ndarray):
            # set_states round trip parks residuals as host numpy;
            # rehydrate lazily like sync_state_context does for state
            r = jnp.asarray(np.asarray(r), dtype=like.dtype)
        return r

    def _set_residual(self, key, val):
        self._store_fn().states[key] = val

    # -- the quantize→wire→dequantize round --------------------------------
    def _encode_bucket(self, b, flat):
        """Error-feedback encode of one bucket flat: quantize
        residual+grad, store the NEW residual (local quantization
        error), return the payload."""
        g = flat._read().astype(jnp.float32)
        key = residual_key(b.indices, b.dtype)
        acc = g + self._residual(key, g)
        codes, scales = encode(acc, self.mode, self.block)
        self._set_residual(
            key, acc - decode(codes, scales, g.shape[0],
                              self.mode, self.block))
        return codes, scales

    def reduce_serial(self, kv, buckets, flats):
        """Serial-path replacement for ``kv.reduce_many`` over whole
        buckets: one quantized payload per bucket, ONE wire call for the
        batch, dequantized in place into each flat."""
        from ..ndarray import NDArray
        payloads, metas = [], []
        for b in buckets:
            flat = flats[id(b)]
            codes, scales = self._encode_bucket(b, flat)
            payloads.append((NDArray(codes, ctx=flat._ctx),
                             NDArray(scales, ctx=flat._ctx)))
            metas.append(int(np.prod(flat.shape)))
        kv.reduce_quantized(payloads, metas, self.mode, self.block)
        for b, (codes_nd, scales_nd), n in zip(buckets, payloads, metas):
            flat = flats[id(b)]
            out = decode(codes_nd._read(), scales_nd._read(), n,
                         self.mode, self.block)
            flat._write(out.astype(flat.dtype))
        return flats

    def reduce_async(self, kv, b, flat, label=None):
        """Overlapped-path replacement for ``kv.reduce_many_async`` of
        one bucket: encode now (mid-backward, inside the scheduler's
        offband section), put the payload on the wire, hand back a
        handle whose ``wait()`` dequantizes into ``flat``."""
        from ..ndarray import NDArray
        codes, scales = self._encode_bucket(b, flat)
        n = int(np.prod(flat.shape))
        inner = kv.reduce_quantized_async(
            [(NDArray(codes, ctx=flat._ctx),
              NDArray(scales, ctx=flat._ctx))],
            [n], self.mode, self.block, label=label)
        return QuantReduceHandle(inner, flat, n, self.mode, self.block)


# -- selftest ---------------------------------------------------------------

def _oracle_int8(x, block):
    nb = n_blocks(x.size, block)
    xp = np.pad(x.astype(np.float64), (0, nb * block - x.size)) \
        .reshape(nb, block)
    s = np.abs(xp).max(axis=1) / 127.0
    safe = np.where(s > 0, s, 1.0)
    c = np.clip(np.round(xp / safe[:, None]), -127, 127)
    return (c * s[:, None]).reshape(-1)[:x.size]


def selftest(verbose=True):
    """Exercised by ``python -m incubator_mxnet_tpu.parallel.quant
    --selftest`` (tools/run_lint.sh tier): kernel round trips vs a
    float64 numpy oracle, the documented error bounds, error-feedback
    convergence, the shard-owner map, and (with ≥2 host devices) the
    virtual-mesh payload collective."""
    rs = np.random.RandomState(0)
    block = 64

    # 1. int8 round trip matches the numpy oracle bit-for-bit in f32
    for n in (1, 63, 64, 65, 1000):
        x = rs.randn(n).astype(np.float32)
        codes, scales = encode(jnp.asarray(x), "int8", block)
        got = np.asarray(decode(codes, scales, n, "int8", block))
        want = _oracle_int8(x, block).astype(np.float32)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-6)
        # documented bound: half a code step per element
        bound = np.abs(np.pad(x, (0, n_blocks(n, block) * block - n))
                       .reshape(-1, block)).max(axis=1) / 254.0 + 1e-7
        err = np.abs(got - x).reshape(-1)
        per_blk = np.pad(err, (0, n_blocks(n, block) * block - n)) \
            .reshape(-1, block).max(axis=1)
        assert (per_blk <= bound + 1e-6).all(), (n, per_blk, bound)

    # 2. 2bit round trip: codes land exactly on {-s, 0, +s}
    x = rs.randn(515).astype(np.float32)
    codes, scales = encode(jnp.asarray(x), "2bit", block)
    got = np.asarray(decode(codes, scales, 515, "2bit", block))
    s_per = np.repeat(np.asarray(scales), block)[:515]
    ok = (np.isclose(got, 0) | np.isclose(got, s_per)
          | np.isclose(got, -s_per))
    assert ok.all()

    # 3. wire bytes: int8 beats dense f32 by ≥ 3.5x at the default block
    n = 1 << 20
    assert 4.0 * n / wire_nbytes(n, "int8", resolve_block()) >= 3.5
    assert 4.0 * n / wire_nbytes(n, "2bit", resolve_block()) >= 3.5

    # 4. error feedback drives quantized-SGD to the float fixed point:
    # constant gradient g, lr 0.25 — after T steps the float path moved
    # T·lr·g exactly; the EF path's cumulative dequantized updates
    # telescope to sum(g) - residual_T, so the gap stays bounded by ONE
    # step's quantization error instead of growing with T.
    g = (rs.randn(256) * np.float32(0.7)).astype(np.float32)
    lr = np.float32(0.25)
    res = jnp.zeros(256, jnp.float32)
    w_q = np.zeros(256, np.float32)
    w_f = np.zeros(256, np.float32)
    gaps = []
    for _ in range(40):
        acc = jnp.asarray(g) + res
        codes, scales = encode(acc, "int8", block)
        deq = decode(codes, scales, 256, "int8", block)
        res = acc - deq
        w_q = w_q - lr * np.asarray(deq)
        w_f = w_f - lr * g
        gaps.append(np.abs(w_q - w_f).max())
    one_step = lr * (np.abs(g).reshape(-1, block).max(axis=1) / 254.0
                     + 1e-6).max() * 2
    assert gaps[-1] <= one_step, (gaps[-1], one_step)
    assert gaps[-1] <= max(gaps[:5]) + 1e-6      # bounded, not growing

    # 5. contiguous shard owners
    assert shard_owners(8, 4) == (0, 0, 1, 1, 2, 2, 3, 3)
    assert shard_owners(3, 8) == (0, 2, 5)
    assert shard_owners(5, 1) == (0, 0, 0, 0, 0)

    # 6. virtual-mesh payload collective reproduces the dequantized sum
    # (per-worker payloads laid onto the mesh directly — the single
    # process plays every rank, like the compression virtual-mesh test)
    devs = jax.devices()
    if len(devs) >= 2:
        from jax.sharding import Mesh
        W = min(4, len(devs))
        mesh = Mesh(np.array(devs[:W]), ("worker",))
        n = 300
        nb = n_blocks(n, block)
        kb = -(-nb // W)
        xs = rs.randn(W, n).astype(np.float32)
        pays = [encode(jnp.asarray(x), "int8", block) for x in xs]
        codes_g = jax.device_put(
            jnp.stack([jnp.pad(c.reshape(nb, block),
                               ((0, kb * W - nb), (0, 0))).reshape(-1)
                       for c, _ in pays]),
            NamedSharding(mesh, P("worker")))
        scales_g = jax.device_put(
            jnp.stack([jnp.pad(s, (0, kb * W - nb)) for _, s in pays]),
            NamedSharding(mesh, P("worker")))
        fn = _payload_reduce_jitted(mesh, W, kb, block, "int8")
        oc, os_ = fn(codes_g, scales_g)
        oc = jnp.asarray(oc).reshape(-1)[:nb * block]
        os_ = jnp.asarray(os_).reshape(-1)[:nb]
        got = np.asarray(decode(oc, os_, n, "int8", block))
        want = np.sum([np.asarray(decode(c, s, n, "int8", block))
                       for c, s in pays], axis=0)
        # re-quantization of the shard sum: one more half-step of error
        scale_bound = np.abs(np.pad(want, (0, nb * block - n))) \
            .reshape(nb, block).max(axis=1) / 254.0 + 1e-6
        err = np.abs(got - want)
        per_blk = np.pad(err, (0, nb * block - n)) \
            .reshape(nb, block).max(axis=1)
        assert (per_blk <= scale_bound + 1e-6).all(), \
            (per_blk.max(), scale_bound.max())
    elif verbose:
        print("quant selftest: <2 devices, mesh leg skipped")

    if verbose:
        print("quant selftest: OK")
    return True


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(prog="incubator_mxnet_tpu.parallel.quant")
    p.add_argument("--selftest", action="store_true",
                   help="run the quant/shard kernel selftest")
    args = p.parse_args(argv)
    if args.selftest:
        selftest()
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
