"""Ring attention: sequence/context parallelism over the mesh "sp" axis.

The reference's long-sequence story is bucketing (SURVEY §5.7); this module
provides the modern capability the TPU build must add: sequences sharded
across devices, attention computed exactly by rotating K/V shards around
the ring with ``ppermute`` over ICI while each device accumulates its Q
shard's online softmax (Ring Attention; the blockwise-parallel formulation).

Communication pattern: P-1 ppermute steps, each overlapped by XLA with the
local (Sq/P × Sk/P) attention block — compute time per block ≫ ICI hop for
realistic shapes, so the ring pipelines cleanly.

Works on any mesh (tested on the 8-device virtual CPU mesh).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask=None):
    """One (local) attention block: returns (unnormalized acc, m, l)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = s.max(axis=-1)                                   # (b, h, q)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype))
    return acc, m, l


def _ring_body(q, k, v, axis_name, causal, scale):
    """Runs on each device: local Q shard attends to all K/V shards as they
    rotate around the ring."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]

    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    m = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def mask_for(src):
        if not causal:
            return None
        q_pos = my * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = src * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        return (q_pos >= k_pos)[None, None]

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # K/V chunk currently held arrived from device (my - i) mod n
        src = (my - i) % n
        blk_acc, blk_m, blk_l = _block_attn(q, k_cur, v_cur, scale,
                                            mask_for(src))
        m_new = jnp.maximum(m, blk_m)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(blk_m - m_new)
        acc = acc * alpha[..., None] + blk_acc * beta[..., None]
        l = l * alpha + blk_l * beta
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m_new, l, k_nxt, v_nxt

    acc, m, l, _, _ = lax.fori_loop(
        0, n, step, (acc, m, l, k, v),
        unroll=True if isinstance(n, int) else False)
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh, axis="sp", causal=False, scale=None):
    """Exact attention over sequence shards.

    q/k/v: (B, H, S, D) GLOBAL arrays (sharded or shardable on S over
    ``axis``). Returns the (B, H, S, D) output with the same sharding.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ring_body, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                           scale=None):
    """Same, but accepts/returns NDArrays (framework surface)."""
    from ..ndarray import NDArray
    qv = q._read() if isinstance(q, NDArray) else q
    kv = k._read() if isinstance(k, NDArray) else k
    vv = v._read() if isinstance(v, NDArray) else v
    sharding = NamedSharding(mesh, P(None, None, axis, None))
    qv = jax.device_put(qv, sharding)
    kv = jax.device_put(kv, sharding)
    vv = jax.device_put(vv, sharding)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, axis, causal,
                                                 scale))(qv, kv, vv)
    return NDArray(out) if isinstance(q, NDArray) else out
