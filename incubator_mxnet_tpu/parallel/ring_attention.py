"""Ring attention: sequence/context parallelism over the mesh "sp" axis.

The reference's long-sequence story is bucketing (SURVEY §5.7); this module
provides the modern capability the TPU build must add: sequences sharded
across devices, attention computed exactly by rotating K/V shards around
the ring with ``ppermute`` over ICI while each device accumulates its Q
shard's online softmax (Ring Attention; the blockwise-parallel formulation).

Communication pattern: P-1 ppermute steps, each overlapped by XLA with the
local (Sq/P × Sk/P) attention block — compute time per block ≫ ICI hop for
realistic shapes, so the ring pipelines cleanly.

Training: ``ring_attention`` carries a custom vjp. The backward makes one
more trip around the ring — each device recomputes its probability tiles
from the saved softmax stats (flash-style rematerialization, O(Sq·Sk/P)
per step, never the full matrix) while the dK/dV accumulators travel with
their K/V blocks and arrive home complete after P hops.

Works on any mesh (tested on the 8-device virtual CPU mesh).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from .._jax_compat import shard_map

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30
_HI = lax.Precision.HIGHEST


def _block_attn(q, k, v, scale, mask=None):
    """One (local) attention block: returns (unnormalized acc, m, l)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, precision=_HI,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = s.max(axis=-1)                                   # (b, h, q)
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(p.dtype), precision=_HI)
    return acc, m, l


def _causal_mask(my, src, sq, sk):
    q_pos = my * sq + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k_pos = src * sk + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return (q_pos >= k_pos)[None, None]


def _ring_body(q, k, v, axis_name, causal, scale):
    """Per-device forward: local Q shard attends to all K/V shards as they
    rotate around the ring.  Returns (out, m, l) — the softmax stats are
    the backward's residuals."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]

    acc = jnp.zeros((b, h, sq, d), jnp.float32)
    m = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, sq), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # K/V chunk currently held arrived from device (my - i) mod n
        src = (my - i) % n
        mask = _causal_mask(my, src, sq, sk) if causal else None
        blk_acc, blk_m, blk_l = _block_attn(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m, blk_m)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(blk_m - m_new)
        acc = acc * alpha[..., None] + blk_acc * beta[..., None]
        l = l * alpha + blk_l * beta
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return acc, m_new, l, k_nxt, v_nxt

    acc, m, l, _, _ = lax.fori_loop(
        0, n, step, (acc, m, l, k, v),
        unroll=True if isinstance(n, int) else False)
    out = acc / jnp.maximum(l, 1e-20)[..., None]
    return out.astype(q.dtype), m, l


def _ring_bwd_body(q, k, v, out, m, l, g, axis_name, causal, scale):
    """Per-device backward: one more trip around the ring.  dQ accumulates
    locally; dK/dV accumulators travel *with* their K/V blocks and return
    home complete after n hops."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    sq, sk = q.shape[2], k.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)   # (b, h, sq)
    # keep (m, l) separate — folding into lse loses log(l) to absorption
    # for rows whose every key is masked (m = -1e30 sentinel)
    l_inv = 1.0 / jnp.maximum(l, 1e-20)

    def step(i, carry):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = (my - i) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32),
                       precision=_HI,
                       preferred_element_type=jnp.float32) * scale
        mask = _causal_mask(my, src, sq, sk) if causal else None
        if mask is not None:
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - m[..., None]) * l_inv[..., None]
        dv_add = jnp.einsum("bhqk,bhqd->bhkd", p, gf, precision=_HI)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_cur.astype(jnp.float32),
                        precision=_HI)
        ds = p * (dp - delta[..., None]) * scale
        if mask is not None:
            # masked logits are forward constants (`where` routes the grad
            # around them): no dQ/dK through them — matters for rows with
            # no visible keys, where p is uniform rather than 0
            ds = jnp.where(mask, ds, 0.0)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_cur.astype(jnp.float32), precision=_HI)
        dk_add = jnp.einsum("bhqk,bhqd->bhkd", ds, qf, precision=_HI)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = lax.ppermute(dk_cur + dk_add, axis_name, perm)
        dv_nxt = lax.ppermute(dv_cur + dv_add, axis_name, perm)
        return dq, k_nxt, v_nxt, dk_nxt, dv_nxt

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(
        0, n, step, (dq0, k, v, dk0, dv0),
        unroll=True if isinstance(n, int) else False)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_attention(q, k, v, mesh, axis="sp", causal=False, scale=None):
    """Exact attention over sequence shards; reverse-mode differentiable.

    q/k/v: (B, H, S, D) GLOBAL arrays (sharded or shardable on S over
    ``axis``). Returns the (B, H, S, D) output with the same sharding.
    """
    out, _, _ = _ring_fwd_stats(q, k, v, mesh, axis, causal, scale)
    return out


def _ring_fwd_stats(q, k, v, mesh, axis, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, None, axis, None)
    stat_spec = P(None, None, axis)
    fn = shard_map(
        functools.partial(_ring_body, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, stat_spec, stat_spec),
        check_vma=False)
    return fn(q, k, v)


def _ring_attention_fwd(q, k, v, mesh, axis, causal, scale):
    out, m, l = _ring_fwd_stats(q, k, v, mesh, axis, causal, scale)
    return out, (q, k, v, out, m, l)


def _ring_attention_bwd(mesh, axis, causal, scale, res, g):
    q, k, v, out, m, l = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, None, axis, None)
    stat_spec = P(None, None, axis)
    fn = shard_map(
        functools.partial(_ring_bwd_body, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, stat_spec, stat_spec, spec),
        out_specs=(spec, spec, spec),
        check_vma=False)
    return fn(q, k, v, out, m, l, g)


ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=False,
                           scale=None):
    """Same, but accepts/returns NDArrays (framework surface)."""
    from ..ndarray import NDArray
    qv = q._read() if isinstance(q, NDArray) else q
    kv = k._read() if isinstance(k, NDArray) else k
    vv = v._read() if isinstance(v, NDArray) else v
    sharding = NamedSharding(mesh, P(None, None, axis, None))
    qv = jax.device_put(qv, sharding)
    kv = jax.device_put(kv, sharding)
    vv = jax.device_put(vv, sharding)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, axis, causal,
                                                 scale))(qv, kv, vv)
    return NDArray(out) if isinstance(q, NDArray) else out
