"""DataParallelTrainer: the fused, sharded training step.

TPU-native replacement for Module's DataParallelExecutorGroup + KVStore
update loop (ref: python/mxnet/module/executor_group.py:129,267 +
gluon/trainer.py:156):

* the whole train step — forward, loss, backward, optimizer update — is ONE
  jitted XLA program (the reference needed engine bulking + fused optimizer
  ops to approximate this; XLA gives it outright),
* the batch is sharded over the mesh "dp" axis; parameters are replicated;
  XLA inserts the gradient all-reduce (psum over ICI) exactly where the
  reference ran Comm::Reduce / NCCL allreduce,
* parameters live on device between steps (donated buffers — no host
  round-trips); ``sync_params()`` writes them back into the Gluon Block.

Works on any mesh: 1 real TPU chip, a v5e slice, or the 8-device virtual
CPU mesh used by tests and the driver's multi-chip dry-run.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ndarray import NDArray
from .. import autograd, random_state
from ..ops.registry import get_op
from .mesh import data_parallel_mesh

__all__ = ["DataParallelTrainer", "pure_optimizer"]


def _spans_processes(sharding):
    """True when the sharding places shards on devices of other processes
    (multi-host mesh) — plain device_put can't reach those."""
    me = jax.process_index()
    return any(d.process_index != me for d in sharding.device_set)


def _global_put(value, sharding):
    """device_put that also works on process-spanning meshes: every process
    builds only its addressable shards from the host value (which multihost
    callers must hold replicated — see _gather_params' broadcast).  This is
    the placement role ps-lite's ZPull played; here it's a local slice-and-
    upload with zero cross-host traffic."""
    if not _spans_processes(sharding):
        return jax.device_put(value, sharding)
    v = np.asarray(value)
    return jax.make_array_from_callback(v.shape, sharding,
                                        lambda idx: v[idx])


def pure_optimizer(name, **hyper):
    """(init_state, update) pair built from the fused optimizer update ops
    (ops/optimizer_ops.py — the same kernels the eager Optimizer uses)."""
    name = name.lower()
    if name == "sgd":
        momentum = hyper.get("momentum", 0.0)
        if momentum:
            op = get_op("sgd_mom_update").fcompute

            def init(w):
                return (jnp.zeros_like(w),)

            def update(w, g, state, lr):
                new_w, new_mom = op(w, g, state[0], lr=lr,
                                    momentum=momentum,
                                    wd=hyper.get("wd", 0.0),
                                    rescale_grad=hyper.get("rescale_grad", 1.0),
                                    clip_gradient=hyper.get("clip_gradient", -1.0))
                return new_w, (new_mom,)
        else:
            op = get_op("sgd_update").fcompute

            def init(w):
                return ()

            def update(w, g, state, lr):
                return op(w, g, lr=lr, wd=hyper.get("wd", 0.0),
                          rescale_grad=hyper.get("rescale_grad", 1.0),
                          clip_gradient=hyper.get("clip_gradient", -1.0)), ()
        return init, update
    if name == "adam":
        op = get_op("adam_update").fcompute
        b1 = hyper.get("beta1", 0.9)
        b2 = hyper.get("beta2", 0.999)

        def init(w):
            return (jnp.zeros_like(w), jnp.zeros_like(w), jnp.zeros((), jnp.int32))

        def update(w, g, state, lr):
            mean, var, t = state
            t = t + 1
            tf = t.astype(jnp.float32)
            lr_t = lr * jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
            new_w, new_mean, new_var = op(
                w, g, mean, var, lr=lr_t, beta1=b1, beta2=b2,
                epsilon=hyper.get("epsilon", 1e-8), wd=hyper.get("wd", 0.0),
                rescale_grad=hyper.get("rescale_grad", 1.0),
                clip_gradient=hyper.get("clip_gradient", -1.0))
            return new_w, (new_mean, new_var, t)
        return init, update
    raise ValueError("pure_optimizer: unsupported optimizer %r "
                     "(sgd and adam cover the fused-step path; others run "
                     "through the eager Trainer)" % name)


class DataParallelTrainer(object):
    """One-jit data-parallel trainer for a Gluon HybridBlock."""

    def __init__(self, block, loss, optimizer="sgd", optimizer_params=None,
                 mesh=None, donate=True, dtype=None):
        """``dtype='bfloat16'`` enables mixed precision: parameters and the
        optimizer stay in f32 master copies; activations and weights are
        cast to bf16 *inside* the jitted step (XLA fuses the casts into the
        convs/matmuls, which then run native bf16 MXU passes); the loss is
        computed in f32.  Same semantics as the reference's mp_sgd
        multi-precision path (src/operator/optimizer_op.cc mp_* ops), but
        the master/compute split lives in the one fused program."""
        self.block = block
        self.loss = loss
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        optimizer_params = dict(optimizer_params or {})
        self._lr = optimizer_params.pop("learning_rate", 0.01)
        self._opt_init, self._opt_update = pure_optimizer(optimizer,
                                                          **optimizer_params)
        self._donate = donate
        self._compute_dtype = jnp.dtype(dtype) if dtype is not None else None
        self._rng_key = None       # device-resident, carried through the step
        self._lr_dev = None        # cached device copy of the learning rate
        self._params = None        # name -> jax array (device-resident)
        self._opt_state = None
        self._trainable = None
        self._jit_cache = {}

    # -- parameter plumbing ------------------------------------------------
    def _gather_params(self, example_x):
        blk_params = self.block.collect_params()
        for p in blk_params.values():
            if p._data is None and p._deferred_init:
                # resolve deferred shapes with one eager pass over the data
                self.block._run_deferred_init(NDArray(example_x))
                break
        repl = NamedSharding(self.mesh, P())
        multihost = _spans_processes(repl)
        vals = {n: p.data()._read() for n, p in blk_params.items()}
        if multihost:
            # rank 0's initialization wins, exactly the reference's
            # KVStore::Init broadcast semantics (kvstore_dist.h — first
            # pushed value defines the key); ONE batched collective
            from jax.experimental import multihost_utils
            vals = {n: np.asarray(v)
                    for n, v in multihost_utils.broadcast_one_to_all(
                        {n: np.asarray(v) for n, v in vals.items()}).items()}
        self._params = {}
        self._param_sharding = {}
        self._trainable = []
        for name, p in blk_params.items():
            spec = P(*p.sharding) if getattr(p, "sharding", None) else P()
            sh = NamedSharding(self.mesh, spec)
            self._param_sharding[name] = sh
            self._params[name] = _global_put(vals[name], sh)
            if p.grad_req != "null":
                self._trainable.append(name)
        # optimizer state shards like its parameter (same layout, so the
        # fused update stays local — reference mp/rowsparse updates were
        # likewise colocated with the weight).  Single-host: init runs on
        # the already-sharded device array, so tp-sharded state is born
        # sharded (never materialized whole on one device); multihost:
        # init runs on the host value and shards go up via _global_put.
        self._opt_state = {}
        for n in self._trainable:
            sh = self._param_sharding[n]
            seed = jnp.asarray(vals[n]) if multihost else self._params[n]
            self._opt_state[n] = jax.tree.map(
                lambda x, sh=sh, n=n: _global_put(
                    x, sh if getattr(x, "ndim", 0) ==
                    len(self._params[n].shape) else repl),
                self._opt_init(seed))

    def sync_params(self):
        """Write device params back into the Block (checkpoint/export path).

        Mesh-sharded buffers are pulled to host first: Block params must be
        plain single-device arrays so eager eval/save work regardless of
        the trainer's mesh.
        """
        blk_params = self.block.collect_params()
        repl = NamedSharding(self.mesh, P())
        gather = None
        for name, v in self._params.items():
            if not v.sharding.is_fully_replicated:
                # tp/ep-sharded buffers: allgather to replicated first so
                # the host fetch sees a fully-addressable array even on
                # multi-host meshes
                if gather is None:
                    gather = jax.jit(lambda a: a, out_shardings=repl)
                v = gather(v)
            blk_params[name].data()._write(jnp.asarray(jax.device_get(v)))

    # -- the pure step -----------------------------------------------------
    def _make_step(self, train=True):
        block, loss_blk = self.block, self.loss
        trainable = list(self._trainable)
        opt_update = self._opt_update
        cdt = self._compute_dtype

        def forward_loss(trainable_vals, frozen_vals, x, y, rng):
            all_vals = dict(frozen_vals)
            if cdt is not None:
                # compute-dtype cast happens inside the differentiated fn so
                # grads arrive back in f32 (cast transpose = cast back).
                # Only *trainable* params are cast: frozen values include BN
                # running stats, which must never be re-quantized to bf16
                # (the momentum blend would drift them every step)
                all_vals.update({n: v.astype(cdt)
                                 if v.dtype == jnp.float32 else v
                                 for n, v in trainable_vals.items()})
                # f32 inputs AND narrow-integer images (uint8/int16 data
                # pipelines) cast on device, keeping host batches
                # cast-free.  int32/int64 inputs are index data (token
                # ids for Embedding) and must NOT be rounded through the
                # compute dtype — bf16 resolves only 256 values per
                # binade, so large vocab ids would land on multiples of
                # 64 (and the top id past the table).
                if x.dtype == jnp.float32 or x.dtype in (
                        jnp.uint8, jnp.int8, jnp.uint16, jnp.int16):
                    x = x.astype(cdt)
            else:
                all_vals.update(trainable_vals)
                if jnp.issubdtype(x.dtype, jnp.integer):
                    x = x.astype(jnp.float32)
            shadows = {n: NDArray(v) for n, v in all_vals.items()}
            ndx, ndy = NDArray(x), NDArray(y)
            with random_state.use_key(rng):
                with autograd._scope(recording=False, training=train):
                    with block._trace_params(shadows):
                        out = block.hybrid_forward_dispatch(ndx)
                    if cdt is not None:
                        out = NDArray(out._read().astype(jnp.float32))
                    per_sample = loss_blk(out, ndy)
            aux = {n: s._read() for n, s in shadows.items() if s._version > 0}
            return jnp.mean(per_sample._read()), aux

        def step(params, opt_state, rng_key, x, y, lr):
            # rng key lives on device across steps: split here, return the
            # next key — no host RNG round trip per step
            next_key, rng = jax.random.split(rng_key)
            tvals = {n: params[n] for n in trainable}
            fvals = {n: v for n, v in params.items() if n not in tvals}
            (loss_val, aux), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(tvals, fvals, x, y, rng)
            new_params = dict(params)
            new_opt = {}
            for n in trainable:
                new_w, new_s = opt_update(params[n], grads[n], opt_state[n], lr)
                new_params[n] = new_w.astype(params[n].dtype)
                new_opt[n] = new_s
            for n, v in aux.items():
                if n not in tvals:
                    new_params[n] = v.astype(new_params[n].dtype)
            return new_params, new_opt, next_key, loss_val

        return step

    def _sharding_trees(self):
        """(param tree, opt-state tree) of NamedShardings — honors
        per-parameter sharding annotations (tp/ep model parallelism)."""
        ptree = dict(self._param_sharding)
        otree = jax.tree.map(lambda x: x.sharding, self._opt_state)
        return ptree, otree

    def compile(self, *example_args):
        """Build + jit the step for the example shapes; returns the jitted fn."""
        if self._params is None:
            self._gather_params(example_args[0])
        key = tuple((tuple(a.shape), str(a.dtype)) for a in example_args)
        if key not in self._jit_cache:
            repl = NamedSharding(self.mesh, P())
            batch = NamedSharding(self.mesh, P("dp"))
            ptree, otree = self._sharding_trees()
            step = self._make_step(train=True)
            self._jit_cache[key] = jax.jit(
                step,
                in_shardings=(ptree, otree, repl, batch, batch, repl),
                out_shardings=(ptree, otree, repl, repl),
                donate_argnums=(0, 1, 2) if self._donate else ())
        return self._jit_cache[key]

    def compile_multi(self, xs, ys):
        """Jit K chained steps as ONE XLA program: lax.scan over the step
        with the (K, batch, ...) data resident on device.  Amortizes
        per-launch dispatch/RPC overhead K× — the jit-level analogue of
        the reference engine's op bulking (threaded_engine.h BulkAppend),
        one level up: whole train steps are the ops being bulked."""
        key = ("multi", tuple(xs.shape), str(xs.dtype), tuple(ys.shape))
        if key not in self._jit_cache:
            repl = NamedSharding(self.mesh, P())
            batch = NamedSharding(self.mesh, P(None, "dp"))
            ptree, otree = self._sharding_trees()
            step = self._make_step(train=True)

            def multi(params, opt_state, rng_key, xs, ys, lr):
                def body(carry, xy):
                    p, s, k = carry
                    x, y = xy
                    p, s, k, loss = step(p, s, k, x, y, lr)
                    return (p, s, k), loss

                (params, opt_state, rng_key), losses = jax.lax.scan(
                    body, (params, opt_state, rng_key), (xs, ys))
                return params, opt_state, rng_key, losses[-1]

            self._jit_cache[key] = jax.jit(
                multi,
                in_shardings=(ptree, otree, repl, batch, batch, repl),
                out_shardings=(ptree, otree, repl, repl),
                donate_argnums=(0, 1, 2) if self._donate else ())
        return self._jit_cache[key]

    def _prepare_inputs(self, data, label, batch_spec, multi=False):
        """Shared dispatch prologue: resolve params (deferred init runs on
        the raw single-device batch, BEFORE mesh sharding), device-resident
        rng/lr, batch arrays laid out per ``batch_spec`` (resharding
        skipped when already placed)."""
        x = data._read() if isinstance(data, NDArray) else data
        y = label._read() if isinstance(label, NDArray) else label
        if self._params is None:
            # the eager deferred-init pass must see the example on the
            # SAME device as the Block's params (default backend), and at
            # compute dtype — host-pinned uint8 pipeline batches are
            # neither, so round-trip through numpy once here
            ex = jnp.asarray(np.asarray(x))
            if jnp.issubdtype(ex.dtype, jnp.integer):
                ex = ex.astype(jnp.float32)
            self._gather_params(ex[0] if multi else ex)
        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(self.mesh, batch_spec)
        multihost = _spans_processes(repl)
        if self._rng_key is None:
            key = random_state.next_key()
            if multihost:
                # one shared dropout/shuffle stream across hosts (ranks
                # must trace identical programs with identical constants)
                from jax.experimental import multihost_utils
                key = multihost_utils.broadcast_one_to_all(key)
            self._rng_key = _global_put(key, repl)
        if self._lr_dev is None:
            self._lr_dev = _global_put(jnp.asarray(self._lr, jnp.float32),
                                       repl)
        def _place(v):
            if not hasattr(v, "sharding"):
                v = np.asarray(v)  # lists / scalars → one host array
            elif v.sharding.is_equivalent_to(batch_sh, v.ndim):
                return v
            if multihost:
                # each process contributes its LOCAL batch shard; jax glues
                # them into the global (world_batch, ...) array — the data-
                # parallel split the reference expressed as per-worker
                # slices of provide_data (executor_group.py DataParallel).
                # The input stays host-side numpy until this single upload
                # (no device bounce on the hot path).
                return jax.make_array_from_process_local_data(batch_sh,
                                                              np.asarray(v))
            return jax.device_put(v, batch_sh)

        return _place(x), _place(y)

    def step_multi(self, datas, labels):
        """Run K chained steps in one launch; ``datas`` (K, batch, ...),
        ``labels`` (K, batch).  Returns the last step's device loss."""
        from .mesh import use_mesh
        with use_mesh(self.mesh):
            # scope covers deferred-init (in _prepare_inputs) AND the
            # trace: mesh-aware layers resolve this mesh throughout
            xs, ys = self._prepare_inputs(datas, labels, P(None, "dp"),
                                          multi=True)
            fn = self.compile_multi(xs, ys)
            self._params, self._opt_state, self._rng_key, loss_val = fn(
                self._params, self._opt_state, self._rng_key, xs, ys,
                self._lr_dev)
        return loss_val

    def step(self, data, label):
        """Run one sharded train step; returns the device scalar loss.

        The trainer's mesh is scoped for the trace (parallel.use_mesh), so
        mesh-aware layers (MultiHeadAttention(seq_axis=...), capacity MoE)
        resolve THIS mesh without the caller wrapping every step."""
        from .mesh import use_mesh
        with use_mesh(self.mesh):
            # scope covers deferred-init (in _prepare_inputs) AND the
            # trace: mesh-aware layers resolve this mesh throughout
            x, y = self._prepare_inputs(data, label, P("dp"))
            fn = self.compile(x, y)
            self._params, self._opt_state, self._rng_key, loss_val = fn(
                self._params, self._opt_state, self._rng_key, x, y,
                self._lr_dev)
        return loss_val

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = lr
        self._lr_dev = None  # re-upload on next step
