"""Multi-host distributed bootstrap + the dist_* KVStore façade.

TPU-native replacement for ps-lite (src/kvstore/kvstore_dist.h) and the
dmlc tracker (tools/launch.py): process coordination is
``jax.distributed.initialize`` (the jax coordination service plays the
scheduler/Postoffice role), data-parallel gradient sync is an XLA
all-reduce over ICI/DCN instead of ZPush/ZPull to servers.

The KVStore *API* survives intact (SURVEY §5.8): init/push/pull/
row_sparse_pull/barrier/rank/num_workers/set_optimizer — scripts written
against dist_sync run unchanged; the transport underneath is collectives.
`dist_async`'s push-immediately semantics are outside XLA's synchronous
model; DistKVStore("dist_async") runs sync with a documented warning
(SURVEY §2.4 marks it a non-goal).
"""
from __future__ import annotations

import logging
import os

import numpy as np
import jax

from ..kvstore import KVStore

__all__ = ["init_process", "rank", "num_workers", "barrier", "DistKVStore"]

_initialized = False


def init_process(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize multi-host jax.distributed (replaces DMLC_ROLE/tracker env
    bootstrap, tools/launch.py:29). Reads standard env vars if args omitted."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MX_COORDINATOR")
    num_processes = num_processes or int(os.environ.get("MX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else (
        int(os.environ["MX_PROCESS_ID"]) if "MX_PROCESS_ID" in os.environ else None)
    if coordinator_address:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _initialized = True


def rank():
    """Worker rank (ref: KVStore::get_rank / MXKVStoreGetRank)."""
    return jax.process_index()


def num_workers():
    """ref: KVStore::get_group_size."""
    return jax.process_count()


def barrier():
    """Global barrier (ref: KVStore::Barrier → ps::Postoffice::Barrier).

    Implemented as a tiny psum across all processes — every host must
    arrive before XLA returns."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("mx_kvstore_barrier")


def num_dead_nodes():
    """ref: MXKVStoreGetNumDeadNode — jax coordination service terminates
    the job on member failure, so a live process always observes 0."""
    return 0


class DistKVStore(KVStore):
    """dist_sync / dist_device_sync / dist_async over jax.distributed."""

    def __init__(self, type_):
        super().__init__(type_)
        if type_ == "dist_async":
            logging.warning(
                "dist_async parameter-server semantics are outside XLA's "
                "synchronous execution model; running synchronously "
                "(equivalent to dist_sync). See SURVEY.md §2.4.")
        init_process()

    def _cross_worker_reduce(self, red):
        """Sum one value across workers over DCN/ICI (compression applied
        by the caller before the wire — 2-bit values in {-t,0,+t} sum
        exactly, ref: gradient_compression.h)."""
        if num_workers() > 1:
            from jax.experimental import multihost_utils
            summed = multihost_utils.process_allgather(red._read())
            red._write(summed.sum(axis=0))
        return red

    def _cross_worker_reduce_many(self, reds):
        """All values of one push in as few collectives as possible:
        same-dtype values pack into one flat buffer (native dtype, so
        integer sums stay exact), allgather-summed once, and unpacked —
        latency-bound DCN rounds amortize over the whole push (the
        batching role of the reference's big-array sharding,
        kvstore_dist.h MXNET_KVSTORE_BIGARRAY_BOUND).  Mutates in place."""
        if num_workers() <= 1 or not reds:
            return reds
        import numpy as np
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        from ..ndarray.sparse import BaseSparseNDArray
        groups = {}
        for r in reds:
            if isinstance(r, BaseSparseNDArray):
                self._cross_worker_reduce(r)    # row-id dedup path
            else:
                groups.setdefault(np.dtype(r.dtype), []).append(r)
        for dtype, group in groups.items():
            vals = [r._read() for r in group]
            flat = jnp.concatenate([v.ravel() for v in vals])
            summed = multihost_utils.process_allgather(flat).sum(axis=0)
            off = 0
            for r, v in zip(group, vals):
                n = int(np.prod(v.shape))
                r._write(jnp.asarray(summed[off:off + n]).reshape(v.shape))
                off += n
        return reds

    def set_optimizer(self, optimizer):
        """dist path: pickle round-trip, as the reference ships the optimizer
        to servers (kvstore.py set_optimizer → _send_command_to_servers)."""
        import pickle
        from .. import optimizer as opt
        self._updater = opt.get_updater(pickle.loads(pickle.dumps(optimizer)))
