"""Multi-host distributed bootstrap + the dist_* KVStore façade.

TPU-native replacement for ps-lite (src/kvstore/kvstore_dist.h) and the
dmlc tracker (tools/launch.py): process coordination is
``jax.distributed.initialize`` (the jax coordination service plays the
scheduler/Postoffice role), data-parallel gradient sync is an XLA
all-reduce over ICI/DCN instead of ZPush/ZPull to servers.

The KVStore *API* survives intact (SURVEY §5.8): init/push/pull/
row_sparse_pull/barrier/rank/num_workers/set_optimizer — scripts written
against dist_sync run unchanged; the transport underneath is collectives.
`dist_async`'s push-immediately semantics are outside XLA's synchronous
model, so — exactly as the reference keeps them outside the device — they
live on a HOST parameter service (parallel/ps.py): rank 0 runs the server
thread, every push is applied the moment it arrives with the server-side
optimizer, pulls return current (stale-tolerant) weights.
"""
from __future__ import annotations

import logging
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analysis import lockstep as _lockstep
from .. import elastic as _elastic
from ..kvstore import KVStore, PullHandle
from ..telemetry import blackbox as _blackbox
from ..telemetry import metrics as _tmetrics
from . import compression

__all__ = ["init_process", "rank", "num_workers", "barrier", "DistKVStore"]

_initialized = False


def init_process(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize multi-host jax.distributed (replaces DMLC_ROLE/tracker env
    bootstrap, tools/launch.py:29). Reads standard env vars if args omitted."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MX_COORDINATOR")
    num_processes = num_processes or int(os.environ.get("MX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else (
        int(os.environ["MX_PROCESS_ID"]) if "MX_PROCESS_ID" in os.environ else None)
    if coordinator_address:
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _initialized = True


def rank():
    """Worker rank (ref: KVStore::get_rank / MXKVStoreGetRank)."""
    return jax.process_index()


def num_workers():
    """ref: KVStore::get_group_size."""
    return jax.process_count()


def barrier():
    """Global barrier (ref: KVStore::Barrier → ps::Postoffice::Barrier).

    Implemented as a tiny psum across all processes — every host must
    arrive before XLA returns."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("mx_kvstore_barrier")


def num_dead_nodes():
    """ref: MXKVStoreGetNumDeadNode — jax coordination service terminates
    the job on member failure, so a live process always observes 0."""
    return 0


# -- in-graph cross-worker reduction ---------------------------------------
_worker_mesh_cache = None
_sum_jit_cache = None


def worker_mesh():
    """1-D mesh with ONE device per process — the collective topology of
    the kvstore wire (the role ps-lite's server group played,
    kvstore_dist.h).  Summing over its "worker" axis lowers to an XLA
    all-reduce that rides DCN between hosts (ICI within a slice)."""
    global _worker_mesh_cache
    if _worker_mesh_cache is None:
        devs, seen = [], set()
        for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
            if d.process_index not in seen:
                seen.add(d.process_index)
                devs.append(d)
        _worker_mesh_cache = Mesh(np.array(devs), ("worker",))
    return _worker_mesh_cache


def _global_sum(flat):
    """Sum one flat buffer across all processes IN-GRAPH: each process
    contributes its shard of a (num_workers, n) global array laid out over
    the worker mesh; a jitted sum(axis=0) with replicated output lowers to
    one XLA all-reduce.  Unlike `multihost_utils.process_allgather` (the
    round-2 wire), the reduction executes inside XLA — no host round-trip
    of the gathered buffer, no Python-side sum, and the payload on the wire
    is the reduce, not an N× gather.  ref: kvstore_dist.h ZPush/ZPull pair
    collapsed into a single all-reduce."""
    global _sum_jit_cache
    mesh = worker_mesh()
    if _sum_jit_cache is None:
        _sum_jit_cache = jax.jit(
            lambda a: a.sum(axis=0),
            out_shardings=NamedSharding(mesh, P()))
    me = jax.process_index()
    my_dev = next(d for d in mesh.devices.flat if d.process_index == me)
    piece = jax.device_put(flat[None], my_dev)
    garr = jax.make_array_from_single_device_arrays(
        (num_workers(),) + tuple(flat.shape),
        NamedSharding(mesh, P("worker")), [piece])
    out = _sum_jit_cache(garr)
    return jnp.asarray(out.addressable_data(0))


_ps_counter = [0]   # SPMD-identical creation index → rendezvous key


# -- graftpulse rank-consistent knob mailbox --------------------------------
#
# The autotuner must never let ranks act on their own local signals — a
# rank-divergent GRAFT_BUCKET_BYTES changes each rank's bucket plan and
# therefore its collective SEQUENCE, which the lockstep auditor would
# (rightly) flag just before the wire deadlocks.  Instead rank 0 parks
# its decision here and the next heartbeat broadcasts it in one extra
# int32 slot of the existing skew allreduce (zero additional
# collectives); every rank — including rank 0 — applies the knob only
# when the broadcast LANDS, so the plan flips on the same step
# everywhere.

_knob_lock = threading.Lock()
_bucket_proposal = [0]


def propose_bucket_bytes(nbytes):
    """Park rank 0's bucket-bytes decision for the next heartbeat
    broadcast.  Called by the autotuner on rank 0 only; other ranks'
    tuners stay observation-only under multi-rank."""
    with _knob_lock:
        _bucket_proposal[0] = int(nbytes)


def _take_bucket_proposal():
    with _knob_lock:
        v = _bucket_proposal[0]
        _bucket_proposal[0] = 0
        return v


class _PSPullHandle(PullHandle):
    """Pull handle whose writes are deferred to wait time: the host
    parameter-service RPC runs on a background thread (issuing it inline
    would block — exactly the wait graftduplex exists to move), and the
    fetched weights are applied at ``wait()``, version-gated per out
    array so a weight the user overwrote between issue and wait keeps
    the user's value (the serial pull-then-write ordering)."""

    __slots__ = ("_fn",)

    def __init__(self, values, fn, label=None, _bracket=None):
        super().__init__(values, label=label, _bracket=_bracket)
        self._fn = fn

    def _materialize(self):
        fn, self._fn = self._fn, None
        if fn is not None:
            self.stale = fn()


class DistKVStore(KVStore):
    """dist_sync / dist_device_sync / dist_async over jax.distributed.

    ``dist_sync``: the wire is an in-graph XLA all-reduce (below).
    ``dist_async``: true parameter-server semantics on a HOST service —
    rank 0 runs a ParameterServer thread applying every push immediately
    with the server-side optimizer; pulls return current (possibly
    stale) weights.  See parallel/ps.py; matches
    kvstore_dist_server.h:306-314 async handling."""

    def __init__(self, type_):
        super().__init__(type_)
        init_process()
        _blackbox.set_rank(rank())      # stamp dumps with this worker
        from ..armor import faults as _faults
        _faults.set_rank(rank())        # rank= clause filters (graftarmor)
        self._hb_step = 0               # dist heartbeat step counter
        self._ps_server = None
        self._ps = None
        self._pull_pool = None          # lazy 1-thread PS client executor
        #                                 (async pulls AND duplex pushes:
        #                                 one worker = FIFO = wire order)
        self._push_futs = []            # in-flight async push futures
        self._push_issue_idx = 0        # submission order, asserted on
        #                                 the wire by lockstep.note_order
        if type_ == "dist_async":
            from . import ps
            idx = _ps_counter[0]
            _ps_counter[0] += 1
            n_srv = int(os.environ.get("MXTPU_PS_NUM_SERVERS", "1"))
            if num_workers() <= 1:
                self._ps_server = ps.ServerGroup(n_srv)
                self._ps = ps.GroupClient(self._ps_server.address, rank=0)
            elif rank() == 0:
                self._ps_server = ps.ServerGroup(n_srv)
                ps.publish_address(self._ps_server.address, idx)
                self._ps = ps.GroupClient(self._ps_server.address, rank=0)
            else:
                self._ps = ps.GroupClient(ps.lookup_address(idx),
                                          rank=rank())
        if self._ps is not None:
            # hand the watchdog a dead-rank source so a trip on a stuck
            # ps_* bracket can NAME the dead peers (satellite: the trip
            # dump carries the dead-rank table).  Weakref: the provider
            # must not keep a closed store alive.
            import weakref
            from ..telemetry import watchdog as _watchdog
            ref = weakref.ref(self)
            def _dead_ranks():
                store = ref()
                if store is None or store._ps is None:
                    return []
                return list(store._ps.dead_nodes(window=5.0))
            _watchdog.register_dead_nodes_provider(_dead_ranks)

    # -- dist_async: the host parameter service -----------------------------
    def _async_np(self, nd_value):
        # native dtype on the wire: integer keys must sum exactly, same
        # contract the sync path keeps (dtype-grouped allreduce below)
        import numpy as _np
        return _np.asarray(nd_value._read())

    def init(self, key, value):
        if self._ps is None:
            return DistKVStore._sync_init(self, key, value)
        super(DistKVStore, self).init(key, value)   # local shapes/dtypes
        keys, values = self._normalize(key, value)
        self._ps.init({str(k): self._async_np(v[0])
                       for k, v in zip(keys, values)})
        barrier()   # every rank sees initialized keys before first push

    def push(self, key, value, priority=0):
        if self._ps is None:
            return super().push(key, value, priority)
        from ..ndarray.sparse import BaseSparseNDArray
        from ..kvstore import _nd_bytes, _wire_bytes
        from ..telemetry import metrics as _tmetrics
        keys, values = self._normalize(key, value)
        batch = {}
        raw_bytes = wire_bytes = 0
        for k, vlist in zip(keys, values):
            if k not in self._store:
                from ..base import MXNetError
                raise MXNetError("key %s has not been initialized" % k)
            red = self._reduce(vlist)
            if isinstance(red, BaseSparseNDArray):
                red = red.tostype("default")
            nb = _nd_bytes(red)
            raw_bytes += nb
            wire_bytes += _wire_bytes(nb, self._compressor)
            if self._compressor is not None:
                red = self._compressor.compress(k, red)
            batch[str(k)] = self._async_np(red)
        _tmetrics.kvstore_push(raw_bytes, wire_bytes)
        if not self._duplex_push_enabled():
            with _blackbox.collective("ps_push", n_keys=len(batch),
                                      nbytes=raw_bytes):
                self._ps.push(batch)    # applied immediately server-side
            return
        # graftduplex push side (ROADMAP, PR 9 follow-up): the reduce/
        # compress above ran on the caller's thread (deterministic
        # content), and the RPCs now ride the SAME 1-thread background
        # client as the async pulls — per ~bucket-size group, so early
        # groups stream to the server while the caller returns to its
        # backward.  One executor worker = FIFO = submission order on
        # the wire, which lockstep.note_order asserts per executed RPC;
        # sync pulls/barriers drain the queue first (read-your-writes).
        from .. import overlap as _overlap
        items = list(batch.items())
        sizes = [v.nbytes for _k, v in items]
        pool = self._pull_executor()
        for group in _overlap.plan_pull_groups(
                list(range(len(items))), sizes, self._push_group_bytes()):
            chunk = {items[i][0]: items[i][1] for i in group}
            nb = sum(sizes[i] for i in group)
            idx = self._push_issue_idx
            self._push_issue_idx += 1
            self._push_futs.append(
                pool.submit(self._ps_push_task, chunk, idx, nb))
        self._reap_pushes()

    _duplex_push_override = None    # tests/benches force on/off

    def _duplex_push_enabled(self):
        """GRAFT_DUPLEX_PUSH (default on): batch dist_async gradient
        pushes onto the background PS client instead of blocking the
        step on the RPC.  Same-worker read-your-writes is preserved
        (sync pulls and barriers drain the queue; async pulls ride the
        same FIFO executor); cross-worker ordering was never promised —
        async SGD staleness is the semantics."""
        if self._ps is None:
            return False
        if self._duplex_push_override is not None:
            return bool(self._duplex_push_override)
        return os.environ.get("GRAFT_DUPLEX_PUSH", "1").strip().lower() \
            not in ("0", "false", "no", "off")

    def _push_group_bytes(self):
        from .. import overlap as _overlap
        try:
            return int(os.environ.get(
                "GRAFT_BUCKET_BYTES", str(_overlap.DEFAULT_BUCKET_BYTES)))
        except ValueError:
            return _overlap.DEFAULT_BUCKET_BYTES

    def _ps_push_task(self, chunk, idx, nbytes):
        """One push group's RPC, on the background client thread.  The
        bracket opens HERE (enter/exit must share a thread), so an RPC
        stuck on a dead server is a named in-flight collective for the
        watchdog; note_order records an issue-order violation if the
        executor ever reorders submissions."""
        _lockstep.note_order("ps_push_async", idx)
        with _blackbox.collective("ps_push_async", n_keys=len(chunk),
                                  nbytes=nbytes):
            self._ps.push(chunk)

    def _reap_pushes(self):
        """Drop completed push futures; surface the first failure at the
        next push instead of never.  Done futures are pruned BEFORE the
        raise, so one failed RPC cannot re-raise its stale exception on
        every later call forever.  A failure surfacing here is already
        POST-RETRY: the PSClient wire retried/reconnected through its
        GRAFT_RPC_RETRIES budget before letting the push task fail, so
        what lands is a PSUnavailableError, not a transient hiccup."""
        pending, failed = [], None
        for f in self._push_futs:
            if not f.done():
                pending.append(f)
                continue
            exc = f.exception()
            if exc is not None and failed is None:
                failed = exc
        self._push_futs = pending
        if failed is not None:
            raise failed

    def _drain_pushes(self):
        """Wait every queued async push (the read-your-writes point:
        sync pulls, barriers, shutdown).  EVERY future is waited even
        when one fails — a caller catching the error must still hold
        read-your-writes for its next sync pull."""
        futs, self._push_futs = self._push_futs, []
        failed = None
        for f in futs:
            try:
                f.result()
            except BaseException as exc:
                if failed is None:
                    failed = exc
        if failed is not None:
            raise failed

    def barrier(self):
        self._drain_pushes()    # a barrier promises peers see our pushes
        super().barrier()

    @staticmethod
    def _quiesce_timeout():
        """GRAFT_QUIESCE_TIMEOUT in seconds (default 30): the drain
        budget for ``quiesce`` — long enough for a queued push burst,
        short enough that a dead peer surfaces as a typed error rather
        than a hung membership fence."""
        try:
            t = float(os.environ.get("GRAFT_QUIESCE_TIMEOUT", "30"))
        except ValueError:
            return 30.0
        return t if t > 0 else 30.0

    def quiesce(self, timeout=None):
        """Drain every in-flight async operation this store owns —
        queued duplex pushes AND anything riding the background pull
        thread — within a deadline (graftelastic: the mandatory prelude
        to a membership re-partition; key ranges must not move under
        live traffic).  Unlike ``_drain_pushes`` (unbounded, the
        read-your-writes point) this wait is BOUNDED: work stuck on a
        dead peer raises :class:`~..armor.errors.QuiesceTimeoutError`
        naming the undrained count instead of hanging the fence, and
        the undrained futures stay owned (``close``/``barrier`` still
        wait them).  A push that FAILED still counts as drained — the
        wire is quiet either way — but the first failure re-raises
        after the drain so the caller sees it.  Returns the number of
        operations drained."""
        from concurrent.futures import wait as _fwait
        from ..armor.errors import QuiesceTimeoutError
        budget = self._quiesce_timeout() if timeout is None \
            else float(timeout)
        t0 = time.monotonic()
        futs, self._push_futs = self._push_futs, []
        if self._pull_pool is not None:
            # a sentinel rides the 1-thread FIFO pull executor: when it
            # runs, every pull submitted before it has finished
            futs = futs + [self._pull_pool.submit(lambda: None)]
        done, not_done = _fwait(futs, timeout=budget)
        if not_done:
            self._push_futs = list(not_done) + self._push_futs
            raise QuiesceTimeoutError(
                "kvstore.quiesce", time.monotonic() - t0, budget,
                pending=len(not_done))
        failed = None
        for f in done:
            exc = f.exception()
            if exc is not None and failed is None:
                failed = exc
        if failed is not None:
            raise failed
        return len(done)

    def close(self):
        """Shut down the background PS client (draining queued pushes),
        the client sockets, and — on the hosting rank — the parameter-
        server threads.  Without this the 1-thread executor and the
        server's accept/handler threads outlive the store (GL204) and
        show up as phantom in-flight work in crash dumps."""
        try:
            self._drain_pushes()
        except Exception:
            pass                # teardown: the job is over either way
        if self._pull_pool is not None:
            self._pull_pool.shutdown(wait=True)
            self._pull_pool = None
        if self._ps is not None:
            try:
                self._ps.close()
            except Exception:
                pass
            self._ps = None
        if self._ps_server is not None:
            try:
                self._ps_server.shutdown()
            except Exception:
                pass
            self._ps_server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass                # interpreter teardown

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        if self._ps is None:
            return super().pull(key, out=out, priority=priority,
                                ignore_sparse=ignore_sparse)
        self._drain_pushes()    # a sync pull reads our own pushes
        import jax.numpy as _jnp
        from ..kvstore import _nd_bytes
        from ..telemetry import metrics as _tmetrics
        assert out is not None
        keys, outs = self._normalize(key, out)
        with _blackbox.collective("ps_pull", n_keys=len(keys)):
            fetched = self._ps.pull([str(k) for k in keys])
        pulled = 0
        for k, olist in zip(keys, outs):
            v = fetched[str(k)]
            for o in olist:
                o._write(_jnp.asarray(v).astype(o.dtype))
                pulled += _nd_bytes(o)
            # refresh the local mirror so row_sparse_pull etc. see it
            self._store[k]._write(_jnp.asarray(v).astype(
                self._store[k].dtype))
        _tmetrics.kvstore_pull(pulled)

    def _pull_executor(self):
        """One background thread for async PS pulls: a single worker
        serializes the GroupClient (it is not thread-safe) and keeps the
        issue order deterministic."""
        if self._pull_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pull_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="graft-ps-pull")
        return self._pull_pool

    def pull_many_async(self, keys, outs, priority=0, label=None):
        """Async weight pull from the host parameter service: the RPC is
        submitted to a background thread at issue time and the fetched
        values are applied at ``wait()`` — version-gated per out array,
        so an array the user overwrote between issue and wait keeps the
        user's bytes (serial pull-then-write ordering) and counts toward
        the handle's ``stale`` total (the consumer's abandon-and-fallback
        signal).  The sync wire (no PS) takes the base issue-time-write
        path."""
        if self._ps is None:
            return super().pull_many_async(keys, outs, priority=priority,
                                           label=label)
        from ..kvstore import _nd_bytes
        keys_n, outs_n = self._normalize(list(keys), outs)
        flat_outs = [o for olist in outs_n for o in olist]
        nbytes = sum(_nd_bytes(o) for o in flat_outs)
        bracket = _blackbox.collective(
            "pull_many_async", n_keys=len(keys_n), keys=keys_n[:4],
            nbytes=nbytes, bucket=label)
        bracket.__enter__()
        entry = getattr(bracket, "entry", None)
        if entry is not None:
            entry["async_pending"] = True
        try:
            fut = self._pull_executor().submit(
                self._ps.pull, [str(k) for k in keys_n])
        except BaseException:
            import sys as _sys
            bracket.__exit__(*_sys.exc_info())
            raise
        versions = [[o._version for o in olist] for olist in outs_n]
        store = self._store

        def _apply():
            import jax.numpy as _jnp
            fetched = fut.result()
            stale = 0
            for k, olist, vers in zip(keys_n, outs_n, versions):
                v = fetched[str(k)]
                # refresh the local mirror (the sync pull does too)
                store[k]._write(_jnp.asarray(v).astype(store[k].dtype))
                for o, ver in zip(olist, vers):
                    if o._version != ver:
                        stale += 1      # overwritten since issue: the
                        continue        # user's write wins
                    o._write(_jnp.asarray(v).astype(o.dtype))
            return stale

        _tmetrics.kvstore_pull(nbytes)
        return _PSPullHandle(flat_outs, _apply, label=label,
                             _bracket=bracket)

    def set_optimizer(self, optimizer):
        if self._ps is None:
            return DistKVStore._sync_set_optimizer(self, optimizer)
        self._drain_pushes()    # updater flip applies to LATER pushes
        self._ps.set_optimizer(optimizer)   # pickled to the server role

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        if self._ps is None:
            return super().row_sparse_pull(key, out=out, priority=priority,
                                           row_ids=row_ids)
        self._drain_pushes()    # row reads must see our own pushes
        import jax.numpy as _jnp
        keys, _ = self._normalize(key, out)
        if row_ids is not None:
            # ship ONLY the requested rows (kvstore_dist_server.h:223):
            # scatter them into the local mirror, then let the base
            # implementation row-select from it
            id_list = row_ids if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(keys)
            for k, ids_nd in zip(keys, id_list):
                ids = np.asarray(ids_nd._read()
                                 if hasattr(ids_nd, "_read")
                                 else ids_nd).astype(np.int64).ravel()
                if not len(ids):
                    continue        # nothing requested: no wire traffic
                rows = self._ps.pull_rows({str(k): ids})[str(k)]
                # scatter ON DEVICE: no full-table host round-trip
                cur = self._store[k]._read()
                self._store[k]._write(cur.at[_jnp.asarray(ids)].set(
                    _jnp.asarray(rows, cur.dtype)))
        else:
            # full refresh: the mirror otherwise holds init-time values
            # forever on the async path
            fetched = self._ps.pull([str(k) for k in keys])
            for k in keys:
                self._store[k]._write(_jnp.asarray(fetched[str(k)]).astype(
                    self._store[k].dtype))
        return super().row_sparse_pull(key, out=out, priority=priority,
                                       row_ids=row_ids)

    def num_dead_nodes(self, node_id=0, timeout_sec=5):
        """Workers whose heartbeats stopped (ref: MXKVStoreGetNumDeadNode,
        kvstore_dist.h:109-115).  Only the async parameter service keeps
        heartbeats; on the sync wire the jax coordination service
        terminates the job on member failure, so a live process always
        observes 0.  Either way the answer is SURFACED, not just
        returned: the ``graft_dist_dead_nodes`` gauge tracks it and a
        nonzero count lands in the flight recorder (graftwatch — a
        silent return left post-mortems blind to the lost worker)."""
        if self._ps is None:
            dead = []
        else:
            dead = list(self._ps.dead_nodes(window=float(timeout_sec)))
        _tmetrics.dist_dead_nodes(len(dead))
        if dead:
            _blackbox.record("dead_nodes", dead=dead,
                             window_s=float(timeout_sec), rank=rank())
        return len(dead)

    def _sync_init(self, key, value):
        """Rank 0's value defines the key globally (ref: kvstore_dist.h
        Init — the first pushed value wins server-side), so workers that
        initialized with different seeds still start in sync."""
        super().init(key, value)
        if num_workers() > 1:
            from jax.experimental import multihost_utils
            keys, _ = self._normalize(key, value)
            vals = {k: np.asarray(self._store[k]._read()) for k in keys}
            vals = multihost_utils.broadcast_one_to_all(vals)
            for k in keys:
                self._store[k]._write(jnp.asarray(vals[k]).astype(
                    self._store[k].dtype))

    def _cross_worker_reduce_sparse(self, red):
        """Union/sum a sparse value across workers.  Row-sparse ships only
        (row_ids, rows) — padded to the global max row count so every
        process issues identically-shaped collectives (the fixed-order
        contract that keeps ranks in lockstep) — then the union rows are
        segment-summed and written back via .data/.indices (sparse arrays
        reject dense in-place writes).  ref: kvstore_dist.h PushRowSparse /
        comm.h ReduceRowSparse."""
        from jax.experimental import multihost_utils
        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(red, RowSparseNDArray):
            idx = np.asarray(red.indices._read()).astype(np.int64)
            dat = np.asarray(red.data._read())
            counts = np.asarray(multihost_utils.process_allgather(
                jnp.asarray([idx.shape[0]], jnp.int32)))
            maxn = max(int(counts.max()), 1)
            pad = maxn - idx.shape[0]
            idx_p = np.concatenate([idx, np.full((pad,), -1, np.int64)])
            dat_p = np.concatenate(
                [dat, np.zeros((pad,) + dat.shape[1:], dat.dtype)])
            g = multihost_utils.process_allgather(
                {"i": jnp.asarray(idx_p), "d": jnp.asarray(dat_p)})
            all_i = np.asarray(g["i"]).reshape(-1)
            all_d = np.asarray(g["d"]).reshape((-1,) + dat.shape[1:])
            keep = all_i >= 0
            all_i, all_d = all_i[keep], all_d[keep]
            uniq, inv = np.unique(all_i, return_inverse=True)
            summed = np.zeros((len(uniq),) + dat.shape[1:], dat.dtype)
            np.add.at(summed, inv, all_d)
            red.data = NDArray(jnp.asarray(summed))
            red.indices = NDArray(jnp.asarray(uniq).astype(
                np.asarray(red.indices._read()).dtype))
            return red
        # CSR (and any future stype): reduce dense, rebuild the compressed
        # form host-side — CSR pushes are rare enough that clarity wins.
        # GUARD: the densify materializes rows*cols on every worker, so
        # above MXTPU_CSR_DENSIFY_BOUND bytes (default 256MB) it switches
        # to a chunked row-band path — each band is densified, summed and
        # re-sparsified separately, bounding peak host memory at the band
        # size.  Band count derives only from shape+bound, so every rank
        # issues the same collective sequence (lockstep contract).
        import os
        import warnings
        bound = int(os.environ.get("MXTPU_CSR_DENSIFY_BOUND", str(1 << 28)))
        nbytes = int(np.prod(red.shape)) * np.dtype(red.dtype).itemsize
        if nbytes <= bound:
            dense = np.asarray(_global_sum(
                red._read().ravel())).reshape(red.shape)
            r, c = np.nonzero(dense)
            red.data = NDArray(jnp.asarray(dense[r, c]))
            red.indices = NDArray(jnp.asarray(c.astype(np.int64)))
            red.indptr = NDArray(jnp.asarray(np.searchsorted(
                r, np.arange(red.shape[0] + 1)).astype(np.int64)))
            return red
        warnings.warn(
            "CSR cross-worker reduce of %s (%d bytes dense) exceeds "
            "MXTPU_CSR_DENSIFY_BOUND=%d; using the chunked row-band path "
            "(slower, bounded memory)" % (red.shape, nbytes, bound))
        nrows, ncols = red.shape
        row_bytes = ncols * np.dtype(red.dtype).itemsize
        band = max(1, bound // max(row_bytes, 1))
        indptr = np.asarray(red.indptr._read()).astype(np.int64)
        indices = np.asarray(red.indices._read()).astype(np.int64)
        data = np.asarray(red.data._read())
        cs, vs, ptr_parts = [], [], [np.zeros(1, np.int64)]
        for r0 in range(0, nrows, band):
            r1 = min(r0 + band, nrows)
            ptr = indptr[r0:r1 + 1]
            dense_b = np.zeros((r1 - r0, ncols), data.dtype)
            if ptr[-1] > ptr[0]:
                rows = np.repeat(np.arange(r0, r1), np.diff(ptr)) - r0
                dense_b[rows, indices[ptr[0]:ptr[-1]]] = \
                    data[ptr[0]:ptr[-1]]
            dense_b = np.asarray(_global_sum(
                dense_b.ravel())).reshape(r1 - r0, ncols)
            r, c = np.nonzero(dense_b)
            cs.append(c)
            vs.append(dense_b[r, c])
            ptr_parts.append(ptr_parts[-1][-1] + np.searchsorted(
                r, np.arange(1, r1 - r0 + 1)).astype(np.int64))
        red.data = NDArray(jnp.asarray(np.concatenate(vs)))
        red.indices = NDArray(jnp.asarray(np.concatenate(cs)))
        red.indptr = NDArray(jnp.asarray(
            np.concatenate(ptr_parts)))
        return red

    def _cross_worker_reduce_many(self, reds, heartbeat=True,
                                  compress=False):
        """All values of one push in as few collectives as possible:
        same-dtype values pack into one flat buffer (native dtype, so
        integer sums stay exact) and go through ONE in-graph all-reduce —
        latency-bound DCN rounds amortize over the whole push (the
        batching role of the reference's big-array sharding,
        kvstore_dist.h MXNET_KVSTORE_BIGARRAY_BOUND).  Iteration order is
        the caller's key order, which every rank derives from the same
        enumerate() over parameters — ranks stay in collective lockstep.
        Mutates in place."""
        if num_workers() <= 1 or not reds:
            return reds
        from ..ndarray.sparse import BaseSparseNDArray
        groups = {}
        for r in reds:
            if isinstance(r, BaseSparseNDArray):
                self._cross_worker_reduce_sparse(r)    # row-id union path
            else:
                groups.setdefault(np.dtype(r.dtype), []).append(r)
        # pack/unpack glue is jitted (engine.flatten_arrays / split_flat)
        # so an N-value push costs 2 dispatches of host glue instead of
        # ~2N (one ravel per value + one slice per write-back)
        from .. import engine as _engine
        # the legacy threshold compressor only applies to per-key PUSH
        # traffic (the caller already quantized to {-t, 0, +t}); bucket
        # flats from reduce_many* arrive compress=False — they either
        # ride dense or went through the block-scaled graftzero wire
        # (_cross_worker_reduce_quantized) before reaching a collective
        compress = compress and (self._compressor is not None)
        for dtype, group in groups.items():
            vals = [r._read() for r in group]
            flat = _engine.flatten_arrays(tuple(vals))
            if compress and np.issubdtype(dtype, np.floating):
                # the push already quantized values to {-t, 0, +t}
                # (residual kept worker-side); the wire is a compressed
                # reduce-scatter (all-to-all of the packed 2-bit shards)
                # + an all-gather of exact int8 shard sums — per-worker
                # bytes are W-INDEPENDENT (~1.25n vs dense's ~8n), unlike
                # the old allgather-of-codes that shipped (W-1)·n/4 and
                # decoded O(W·n) per worker
                # (gradient_compression.h:37-132 + kvstore_dist_server.h
                # DataHandleCompressed, sharded across workers)
                t = self._compressor.threshold
                words = compression.encode_2bit(flat, t)
                summed = compression.allreduce_packed_sum(
                    words, t, flat.shape[0], worker_mesh()).astype(flat.dtype)
            else:
                summed = _global_sum(flat)
            pieces = _engine.split_flat(summed, [v.shape for v in vals])
            for r, piece in zip(group, pieces):
                r._write(piece)
        # graftwatch straggler detection piggybacks on this sync path:
        # one tiny extra allreduce per reduce BATCH (not per key) carries
        # every worker's arrival timestamp + step counter.  Gated on the
        # recorder switch, which therefore must be set CONSISTENTLY
        # across ranks (collective-lockstep contract) — see docs.  Async
        # issues (graftlap, heartbeat=False) skip it: reading the
        # heartbeat table host-side blocks on everything dispatched
        # before it on the same devices, which would turn the async
        # issue into a synchronous reduce.  Every rank derives
        # ``heartbeat`` from the same code path, so the collective
        # sequence stays in lockstep.
        if heartbeat and _blackbox.enabled():
            self._heartbeat_skew()
        return reds

    def _cross_worker_reduce_quantized(self, payloads, n_elems, mode,
                                       block, heartbeat=True):
        """graftzero: one EQuARX-style quantized collective per bucket
        payload — all-to-all of the packed codes + scales shards,
        per-shard dequant + f32 sum, re-quantize, narrow all-gather
        (``parallel.quant.reduce_payload_sum``; no f32 collective).
        Mutates the payload NDArrays in place; same heartbeat piggyback
        contract as the dense reduce."""
        if num_workers() <= 1 or not payloads:
            return payloads
        from . import quant as _quant
        mesh = worker_mesh()
        for (codes, scales), n in zip(payloads, n_elems):
            oc, osc = _quant.reduce_payload_sum(
                codes._read(), scales._read(), int(n), mode, int(block),
                mesh)
            codes._write(oc)
            scales._write(osc)
        if heartbeat and _blackbox.enabled():
            self._heartbeat_skew()
        return payloads

    def heartbeat(self):
        """One worker heartbeat on demand (the Trainer's overlapped-step
        wait side): same gating as the reduce-batch piggyback — recorder
        on (rank-consistent, lockstep contract) and real peers."""
        if num_workers() > 1 and _blackbox.enabled():
            self._heartbeat_skew()

    def _heartbeat_skew(self):
        """Per-worker step heartbeat: each rank contributes its arrival
        time (ms, int32 — jax x64 is off and float32 cannot hold epoch
        milliseconds) and step count in its own slot of a (2W,) vector;
        the allreduce sum hands every rank the full table.  Feeds the
        per-step worker-skew histogram, the flight recorder's last-seen
        table, and a straggler log line when the skew is extreme.

        With GRAFT_LOCKSTEP_CHECK on (default; set it IDENTICALLY on
        every rank — the vector SHAPE depends on it) the vector widens
        to (6W,) and additionally carries each rank's collective-stream
        rolling hash + FOLD COUNT (the audited-stream position, NOT the
        wire seq — ps_* brackets skew wire seqs rank-dependently; see
        analysis/lockstep.py) PLUS the lagged-prefix pair (the rolling
        hash as it stood GRAFT_LOCKSTEP_LAG folds earlier): every rank
        then cross-checks the table and a rank whose stream diverged is
        named BEFORE a mispaired collective turns into a silent hang —
        and when the accumulated prefix points bracket the divergence
        to adjacent folds, observe() pins the EXACT collective online
        (PR 10's online-bisection carry-forward)."""
        W = num_workers()
        self._hb_step += 1
        now_ms = int(time.time() * 1000) % (1 << 31)
        audit = _lockstep.enabled()
        elastic = _elastic.enabled()
        # +1 trailing slot: the graftpulse knob broadcast (rank 0's
        # bucket-bytes proposal; 0 = nothing pending).  Same collective,
        # same shape on every rank — the lockstep hash stays in step.
        base_slots = (6 if audit else 2) * W
        # graftelastic: W MORE per-rank slots after the proposal carry
        # each rank's membership epoch, so a survivor that fenced a
        # change names the laggards on the very next heartbeat.  The
        # SHAPE depends on GRAFT_ELASTIC — set it IDENTICALLY on every
        # rank, exactly like the audit knob above.
        vec = np.zeros((base_slots + 1 + (W if elastic else 0),), np.int32)
        vec[rank()] = now_ms
        vec[W + rank()] = self._hb_step % (1 << 31)
        if audit:
            folds, rolling, lag_fold, lag_hash = _lockstep.state_lagged()
            vec[2 * W + rank()] = rolling
            vec[3 * W + rank()] = folds % (1 << 31)
            vec[4 * W + rank()] = lag_hash
            vec[5 * W + rank()] = lag_fold % (1 << 31)
        if elastic:
            vec[base_slots + 1 + rank()] = _lockstep.epoch() % (1 << 31)
        if rank() == 0:
            vec[base_slots] = _take_bucket_proposal() % (1 << 31)
        out = np.asarray(_global_sum(jnp.asarray(vec))).astype(np.int64)
        ts_ms, steps = out[:W], out[W:2 * W]
        if audit:
            hashes, folds_by_rank = out[2 * W:3 * W], out[3 * W:4 * W]
            lag_hashes, lag_folds = out[4 * W:5 * W], out[5 * W:6 * W]
        prop = int(out[base_slots])
        if prop > 0:
            # every rank applies on the SAME heartbeat (rank 0 included:
            # it too deferred its own decision to the broadcast landing)
            try:
                from ..telemetry import autotune as _autotune
                _autotune.apply_bucket_bytes_broadcast(prop)
            except Exception:
                pass
            _lockstep.observe({r: (int(folds_by_rank[r]), int(hashes[r]),
                                   int(lag_folds[r]), int(lag_hashes[r]))
                               for r in range(W)}, my_rank=rank())
        if elastic:
            epochs = out[base_slots + 1:base_slots + 1 + W]
            mine = int(epochs[rank()])
            ahead = int(epochs.max())
            if ahead > mine:
                # only the LAGGARD raises: peers that already fenced the
                # change keep going; this rank must stop issuing
                # collectives against the stale view and apply its
                # pending change (or rejoin) before the next step
                from ..armor.errors import MembershipChangedError
                raise MembershipChangedError(
                    mine, ahead, detail="rank(s) %s heartbeat at a newer "
                    "membership epoch — apply the pending change at the "
                    "step fence before the next collective" % sorted(
                        r for r in range(W) if int(epochs[r]) > mine))
        # mod-wrap unwrap: a rank that crossed the 2^31 ms boundary while
        # others have not would otherwise read as ~24 days of skew
        if ts_ms.max() - ts_ms.min() > (1 << 30):
            ts_ms = np.where(ts_ms < (1 << 30), ts_ms + (1 << 31), ts_ms)
        skew = float(ts_ms.max() - ts_ms.min()) / 1e3
        _tmetrics.dist_worker_skew(skew)
        base = max(int(ts_ms.max()), now_ms)
        # this rank's lag behind the freshest arrival: an upper-bound
        # clock-offset estimate stamped into dump headers so the trace
        # aggregator can align a LONE dump (matched heartbeat/collective
        # anchors are preferred when several ranks' artifacts are given)
        _blackbox.set_clock_offset(float(base - ts_ms[rank()]) / 1e3)
        _blackbox.workers_seen(
            {r: {"lag_s": round(float(base - ts_ms[r]) / 1e3, 6),
                 "step": int(steps[r])} for r in range(W)},
            skew=skew, step=self._hb_step)

    def _sync_set_optimizer(self, optimizer):
        """dist_sync path: pickle round-trip, as the reference ships the
        optimizer to servers (kvstore.py set_optimizer →
        _send_command_to_servers); the updater runs store-side locally."""
        import pickle
        from .. import optimizer as opt
        self._updater = opt.get_updater(pickle.loads(pickle.dumps(optimizer)))
