"""Pass 4 — static concurrency lint (GL2xx) over the package sources.

The op-contract linter (pass 1) guards the registry; these rules guard
the THREADED half of the codebase — the overlap schedulers, the
flight-recorder/watchdog threads, the parameter-service threads and the
data pipeline — where a latent bug is a rare hang in a multi-hour run
rather than a red test.  All rules are AST scans over the package's own
``.py`` sources (the same sources-as-truth approach as pass 1):

=======  ==============================================================
GL201    lock-order inversion: ``with <lockA>: ... with <lockB>``
         somewhere and ``with <lockB>: ... with <lockA>`` elsewhere —
         a cycle in the lexical lock-acquisition graph is a deadlock
         waiting for the right interleaving
GL202    module-global state written from a thread-entry function
         (``threading.Thread(target=...)`` targets and ``run`` methods
         of Thread subclasses) outside any ``with <lock>`` block
GL203    incomplete ``_sched_*`` host protocol: a class implementing
         part of the BucketScheduler host surface silently breaks the
         scheduler at runtime (the protocol is duck-typed)
GL204    a class that starts daemon threads / thread-pool executors but
         defines no shutdown path (``close``/``shutdown``/``stop``/
         ``__del__``/``__exit__``/``_stop_threads``) — its threads leak
         past the owner's lifetime and show up as phantom in-flight
         work in crash dumps
=======  ==============================================================

Suppression: a ``# graftlint: disable=GLxxx <reason>`` comment on the
flagged line or the line directly above silences that finding (the
``--`` separator of the pass-1 decorator syntax is also accepted).
Findings anchor to real file:line sites, so suppressions live exactly
where the deviation is.

Lock identity is heuristic by design: any ``with`` context whose dotted
name's last segment contains ``lock`` (case-insensitive) is treated as
a lock; ``self._x_lock`` keys on the enclosing class, module globals on
the module.  The acquisition graph is lexical (nested ``with`` blocks
within one function) PLUS one interprocedural call level (PR 12): a
call made while holding lock A contributes an edge A → every lock the
CALLEE's own body acquires.  Call resolution is deliberately
conservative — ``self.meth(...)`` resolves to methods of the same
class (cross-file when class names match, like lock identity), a bare
``name(...)`` to same-module top-level functions; dotted/imported
calls and deeper chains stay out of scope (documented in
docs/static_analysis.md).
"""
from __future__ import annotations

import ast
import os
import re

from .contracts import Diagnostic

__all__ = ["RULES", "SCHED_PROTOCOL", "lint_source", "lint_file",
           "lint_package", "package_root"]

RULES = {
    "GL201": "lock-order inversion in the lexical lock-acquisition graph",
    "GL202": "module-global written from a thread target without a lock",
    "GL203": "incomplete _sched_* scheduler host protocol",
    "GL204": "daemon thread/executor owner without a shutdown path",
}

SCHED_PROTOCOL = ("_sched_entries", "_sched_eligible", "_sched_kv",
                  "_sched_flat", "_sched_pass_id", "_sched_label")

_SHUTDOWN_METHODS = {"close", "shutdown", "stop", "__del__", "__exit__",
                     "_stop_threads"}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z0-9, ]+?)\s*(?:(?:--|\s)\s*(.*))?$")


def _line_suppressions(source):
    """{lineno: {code: reason}} for every suppression comment."""
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            why = (m.group(2) or "").strip() or None
            codes = {c: why for c in m.group(1).replace(" ", "").split(",")
                     if c}
            out[i] = codes
    return out


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _lock_key(expr, module, cls):
    """Identity of a lock-ish ``with`` context, or None.  ``self.x`` keys
    on the enclosing class, bare/module names on the module — cross-file
    graphs only join when both the scope and the name agree."""
    dotted = _dotted(expr)
    if not dotted or "lock" not in dotted[-1].lower():
        return None
    if dotted[0] == "self" and len(dotted) >= 2:
        return ("%s.%s" % (cls, dotted[-1])) if cls else None
    return "%s.%s" % (module, dotted[-1])


class _FileFacts(object):
    """Everything one file contributes: lock edges, thread facts,
    per-rule findings local to the file."""

    def __init__(self, filename, module):
        self.filename = filename
        self.module = module
        self.lock_edges = []        # (held_key, inner_key, line)
        self.lock_sites = {}        # key -> first (file, line)
        self.findings = []          # (code, line, message)
        self.fn_locks = {}          # callee key -> set(lock keys its own
        #                             body acquires); callee keys are
        #                             ("c", ClassName, meth) for methods,
        #                             ("m", module, name) for top-level
        #                             functions
        self.held_calls = []        # (held tuple, callee key, line) —
        #                             calls made while holding a lock
        #                             (the one-level interprocedural
        #                             GL201 inputs)


def _callee_key(call, module, cls):
    """Conservative identity of a called function for the one-level
    lock propagation, or None for anything we will not resolve
    (imported/dotted calls, computed callees)."""
    func = call.func
    if isinstance(func, ast.Name):
        return ("m", module, func.id)
    if isinstance(func, ast.Attribute) \
            and isinstance(func.value, ast.Name) \
            and func.value.id == "self" and cls is not None:
        return ("c", cls, func.attr)
    return None


def _walk_locks(body, held, facts, module, cls, fn_key=None):
    """Lexical lock-nesting walk: record an edge held -> new for every
    ``with`` whose context looks like a lock, the set of locks each
    function's own body acquires, and every call made under a held
    lock (the interprocedural one-level inputs)."""
    for node in body:
        new_held = held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                key = _lock_key(item.context_expr, module, cls)
                if key is None and isinstance(item.context_expr, ast.Call):
                    key = _lock_key(item.context_expr.func, module, cls)
                if key is not None:
                    facts.lock_sites.setdefault(
                        key, (facts.filename, node.lineno))
                    for h in new_held:
                        if h != key:
                            facts.lock_edges.append((h, key, node.lineno))
                    if fn_key is not None:
                        facts.fn_locks.setdefault(fn_key, set()).add(key)
                    acquired.append(key)
            new_held = held + tuple(acquired)
        if isinstance(node, ast.ClassDef):
            _walk_locks(node.body, (), facts, module, node.name)
            continue
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if fn_key is not None:
                # a def NESTED inside a function: key it off the callee
                # namespace ("x" kind is unreachable from _callee_key)
                # — merging a local closure's lock summary with a
                # same-named top-level function or method elsewhere
                # would fabricate interprocedural edges and false GL201
                # cycles
                sub_key = ("x", fn_key, node.name)
            elif cls is not None:
                sub_key = ("c", cls, node.name)
            else:
                sub_key = ("m", module, node.name)
            facts.fn_locks.setdefault(sub_key, set())
            _walk_locks(node.body, (), facts, module, cls, fn_key=sub_key)
            continue
        if isinstance(node, ast.Call) and new_held:
            callee = _callee_key(node, module, cls)
            if callee is not None:
                facts.held_calls.append((new_held, callee, node.lineno))
        _walk_locks(list(ast.iter_child_nodes(node)), new_held, facts,
                    module, cls, fn_key=fn_key)


def _is_thread_call(call):
    d = _dotted(call.func)
    return d is not None and d[-1] == "Thread"


def _is_executor_call(call):
    d = _dotted(call.func)
    return d is not None and d[-1] in ("ThreadPoolExecutor",
                                       "ProcessPoolExecutor")


def _thread_entry_names(tree):
    """Function/method names used as thread bodies: ``target=`` of any
    Thread(...) call, plus ``run`` of Thread subclasses."""
    entries = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_thread_call(node):
            for kw in node.keywords:
                if kw.arg == "target":
                    d = _dotted(kw.value)
                    if d:
                        entries.add(d[-1])
        if isinstance(node, ast.ClassDef):
            bases = {(_dotted(b) or ("",))[-1] for b in node.bases}
            if "Thread" in bases:
                entries.add("run")
    return entries


def _check_thread_globals(fn, facts, module):
    """GL202: stores to ``global``-declared names in a thread-entry
    function, outside any lock-ish ``with`` block."""
    declared = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            declared.update(node.names)
    if not declared:
        return

    def walk(body, held):
        for node in body:
            new_held = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # any lock-ish context counts as a guard here, including
                # ``self._lock`` (identity does not matter for GL202)
                if any(_lock_key(i.context_expr, module, "?")
                       for i in node.items):
                    new_held = held + 1
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id in declared \
                        and new_held == 0:
                    facts.findings.append((
                        "GL202", node.lineno,
                        "thread entry %r writes module-global %r outside "
                        "any lock — concurrent with every other writer "
                        "of that global" % (fn.name, t.id)))
            walk(list(ast.iter_child_nodes(node)), new_held)

    walk(fn.body, 0)


def _class_method_names(cls_node):
    names = set()
    for node in cls_node.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _check_sched_protocol(cls_node, facts):
    """GL203: partial ``_sched_*`` surface."""
    names = _class_method_names(cls_node)
    sched = {n for n in names if n.startswith("_sched_")}
    if not sched:
        return
    missing = [m for m in SCHED_PROTOCOL if m not in names]
    if missing:
        facts.findings.append((
            "GL203", cls_node.lineno,
            "class %r implements %d _sched_* member(s) but is missing "
            "%s — BucketScheduler hosts are duck-typed and fail only at "
            "arm/issue time" % (cls_node.name, len(sched),
                                ", ".join(missing))))


def _spawns_daemon(call):
    """Thread(...) with daemon=True (incl. super().__init__ of a Thread
    subclass), or any thread-pool executor construction."""
    if isinstance(call.func, ast.Attribute) and call.func.attr == "__init__":
        # super().__init__(..., daemon=True) inside a Thread subclass
        return any(kw.arg == "daemon"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in call.keywords)
    if _is_executor_call(call):
        return True
    if _is_thread_call(call):
        return any(kw.arg == "daemon"
                   and isinstance(kw.value, ast.Constant)
                   and kw.value.value is True for kw in call.keywords)
    return False


def _check_daemon_shutdown(cls_node, facts):
    """GL204: a class spawning daemon threads/executors with no shutdown
    method."""
    names = _class_method_names(cls_node)
    if names & _SHUTDOWN_METHODS:
        return
    for node in ast.walk(cls_node):
        if isinstance(node, ast.Call) and _spawns_daemon(node):
            what = "thread pool" if _is_executor_call(node) \
                else "daemon thread"
            facts.findings.append((
                "GL204", node.lineno,
                "class %r starts a %s but defines no shutdown path "
                "(one of %s) — the thread outlives its owner and shows "
                "up as phantom in-flight work in crash dumps"
                % (cls_node.name, what,
                   "/".join(sorted(_SHUTDOWN_METHODS)))))
            return                      # one finding per class suffices


def _scan_file(source, filename, module):
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as exc:
        facts = _FileFacts(filename, module)
        facts.findings.append((
            "GL201", exc.lineno or 1,
            "file does not parse (%s) — concurrency lint skipped" % exc))
        return facts
    facts = _FileFacts(filename, module)
    _walk_locks(tree.body, (), facts, module, None)
    entries = _thread_entry_names(tree)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in entries:
            _check_thread_globals(node, facts, module)
        if isinstance(node, ast.ClassDef):
            _check_sched_protocol(node, facts)
            _check_daemon_shutdown(node, facts)
    return facts


def _find_cycles(edges):
    """Cycles in the acquisition graph; returns one representative edge
    list per cycle (deduped by node set)."""
    graph = {}
    for a, b, _line in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen = [], set()

    def dfs(start, node, path):
        for nxt in graph.get(node, ()):
            if nxt == start and len(path) >= 1:
                key = frozenset(path + (nxt,))
                if key not in seen:
                    seen.add(key)
                    cycles.append(path + (nxt,))
            elif nxt not in path and len(path) < 6:
                dfs(start, nxt, path + (nxt,))

    for n in sorted(graph):
        dfs(n, n, (n,))
    return cycles


def _diagnostics(facts_list, suppress_by_file):
    diags = []

    def emit(code, site, filename, line, message):
        sup = suppress_by_file.get(filename, {})
        codes = dict(sup.get(line, {}))
        codes.update(sup.get(line - 1, {}))
        diags.append(Diagnostic(code, site, message, filename, line,
                                suppressed=code in codes,
                                justification=codes.get(code)))

    # GL201: cycles over the union graph (cross-file, keys must match)
    all_edges, sites = [], {}
    for facts in facts_list:
        for a, b, line in facts.lock_edges:
            all_edges.append((a, b, line))
            sites.setdefault((a, b), (facts.filename, line))
    # interprocedural one-level propagation: a call under lock A to a
    # function whose own body acquires B is an A -> B edge, exactly as
    # if the body were inlined one level (deeper chains stay out of
    # scope — the summaries are per-body, not transitive)
    fn_locks = {}
    for facts in facts_list:
        for key, locks in facts.fn_locks.items():
            fn_locks.setdefault(key, set()).update(locks)
    for facts in facts_list:
        for held, callee, line in facts.held_calls:
            for inner in fn_locks.get(callee, ()):
                for h in held:
                    if h != inner:
                        all_edges.append((h, inner, line))
                        sites.setdefault((h, inner),
                                         (facts.filename, line))
    for cycle in _find_cycles(all_edges):
        first = sites.get((cycle[0], cycle[1]),
                          (facts_list[0].filename if facts_list else "?", 1))
        emit("GL201", cycle[0], first[0], first[1],
             "lock-order inversion: acquisition cycle %s — the converse "
             "nesting exists elsewhere; pick one global order or merge "
             "the locks" % " -> ".join(cycle))
    for facts in facts_list:
        for code, line, message in facts.findings:
            emit(code, facts.module, facts.filename, line, message)
    return diags


def lint_source(source, filename="<memory>", module=None):
    """Lint one source string (fixture tests)."""
    module = module or os.path.splitext(os.path.basename(filename))[0]
    facts = _scan_file(source, filename, module)
    return _diagnostics([facts],
                        {filename: _line_suppressions(source)})


def lint_file(path):
    with open(path) as f:
        source = f.read()
    return lint_source(source, filename=path)


def package_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_package(root=None):
    """Lint every ``.py`` file under the package (cross-file GL201
    graph; per-file GL202-204)."""
    root = root or package_root()
    facts_list, suppress = [], {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, os.path.dirname(root))
            module = rel[:-3].replace(os.sep, ".")
            try:
                with open(path) as f:
                    source = f.read()
            except OSError:
                continue
            facts_list.append(_scan_file(source, path, module))
            suppress[path] = _line_suppressions(source)
    return _diagnostics(facts_list, suppress)
