"""Pass 1 — the op-contract linter.

The registry's design bet (one registration serving eager, autograd and
symbolic execution; registry.py, SURVEY §7) means every contract field is
load-bearing three times over: ``num_inputs`` feeds the front-end arg
binder AND the symbol executor, ``nograd_inputs`` drives both the eager
tape skip and the segment-vjp input set (engine._rec_reachable_ext),
``needs_rng``/``takes_is_train`` decide what kwargs dispatch injects.  A
malformed registration therefore corrupts all three modes at once and
nothing surfaced it until a user hit the broken path.

This module verifies each registered ``Operator`` against its fcompute —
the *signature* via inspect and the *body* via AST (``inspect.getsource``
+ ``ast.parse``, i.e. the ops/*.py sources themselves) — and reports
``Diagnostic`` records with stable codes:

=======  ==============================================================
GL101    num_inputs disagrees with the fcompute positional arity
         (incl. variadic ``num_inputs=None`` over a fixed-arity body)
GL102    nograd_inputs index out of range
GL103    mutate_inputs index out of range
GL104    needs_rng promised but no ``rng`` kwarg (or the converse)
GL105    takes_is_train promised but no ``is_train`` kwarg (or converse)
GL106    input_names inconsistent with arity / positional names,
         incl. the ``no_bias`` removal path in ``Operator.arg_names``
GL107    registration collision: a name rebound to a different Operator
GL108    impure fcompute: host-side calls (numpy on array inputs,
         Python RNG, I/O) that break jax.jit AND shape inference —
         ``jax.eval_shape`` runs the same function (no-FInferShape design)
GL109    fcompute returns differing output counts but the registration
         declares a fixed num_outputs and no fnum_outputs
GL110    aux_input_names not a subset of input_names
=======  ==============================================================

Intentional deviations are silenced in-source::

    # graftlint: disable=GL108 -- host callback op, impurity is the point
    @register("my_op", ...)

placed anywhere between the first decorator line and the ``def`` line
(or on the line directly above the first decorator).
"""
from __future__ import annotations

import ast
import inspect
import re
import textwrap

__all__ = ["Diagnostic", "RULES", "lint_operator", "lint_all",
           "suppressions_for"]

RULES = {
    "GL101": "arity mismatch between num_inputs and the fcompute signature",
    "GL102": "nograd_inputs index out of range",
    "GL103": "mutate_inputs index out of range",
    "GL104": "needs_rng contract broken (rng kwarg missing or undeclared)",
    "GL105": "takes_is_train contract broken (is_train kwarg missing or "
             "undeclared)",
    "GL106": "input_names inconsistent with the fcompute arity/names",
    "GL107": "registration collision: name rebound to a different Operator",
    "GL108": "impure fcompute: host call that breaks jit/eval_shape",
    "GL109": "divergent return arity without fnum_outputs",
    "GL110": "aux_input_names not a subset of input_names",
}

# Call targets that are host-side by construction: executing one inside a
# traced fcompute either crashes under jit or silently forks RNG state
# off the reproducible key chain (random_ops.py header).
_IMPURE_PREFIXES = (
    ("np", "random"), ("numpy", "random"),
    ("random",),                       # Python stdlib RNG module
    ("time",),                         # wall-clock reads inside a trace
    ("os", "environ"),
)
_IMPURE_BUILTINS = {"open", "print", "input"}


class Diagnostic:
    """One linter finding (machine-readable via :meth:`as_dict`)."""

    __slots__ = ("code", "op_name", "message", "file", "line",
                 "suppressed", "justification")

    def __init__(self, code, op_name, message, file=None, line=None,
                 suppressed=False, justification=None):
        self.code = code
        self.op_name = op_name
        self.message = message
        self.file = file
        self.line = line
        self.suppressed = suppressed
        self.justification = justification

    def as_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    def __repr__(self):
        tag = " [suppressed]" if self.suppressed else ""
        loc = "%s:%s" % (self.file, self.line) if self.file else "<builtin>"
        return "%s %s (%s)%s: %s" % (self.code, self.op_name, loc, tag,
                                     self.message)


# ---------------------------------------------------------------------------
# fcompute introspection
# ---------------------------------------------------------------------------

def _sig_info(fcompute):
    """Positional-arity facts of an fcompute, or None when uninspectable.

    ``pos_required_only`` counts required POSITIONAL-ONLY params — the
    ones dispatch can never satisfy through the params dict (everything
    POSITIONAL_OR_KEYWORD is keyword-bindable by ``Operator.bind``'s
    ``functools.partial(fcompute, **params)``, so a required tunable like
    count_sketch's ``out_dim`` is a valid contract, not an arity error)."""
    try:
        sig = inspect.signature(fcompute)
    except (TypeError, ValueError):
        return None
    pos_required = pos_total = pos_required_only = 0
    has_varargs = has_varkw = False
    pos_names = []
    kw_names = set()
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            pos_total += 1
            pos_names.append(p.name)
            if p.default is inspect.Parameter.empty:
                pos_required += 1
                if p.kind is p.POSITIONAL_ONLY:
                    pos_required_only += 1
            if p.kind is p.POSITIONAL_OR_KEYWORD:
                kw_names.add(p.name)
        elif p.kind is p.VAR_POSITIONAL:
            has_varargs = True
        elif p.kind is p.KEYWORD_ONLY:
            kw_names.add(p.name)
        elif p.kind is p.VAR_KEYWORD:
            has_varkw = True
    return {"pos_required": pos_required, "pos_total": pos_total,
            "pos_required_only": pos_required_only,
            "pos_names": pos_names, "kw_names": kw_names,
            "has_varargs": has_varargs, "has_varkw": has_varkw}


def _fcompute_tree(fcompute):
    """Top-level FunctionDef AST of the fcompute, or None (C callables,
    REPL definitions, lambdas)."""
    try:
        src = textwrap.dedent(inspect.getsource(fcompute))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    return None


def _toplevel_nodes(fn_node):
    """Walk the function body, NOT descending into nested function/lambda
    bodies — nested defs are closures (custom_vjp rules, host callbacks)
    with their own execution context."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _dotted(node):
    """A Call's target as a dotted-name tuple, e.g. np.random.rand ->
    ('np', 'random', 'rand'); None for computed targets."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z0-9, ]+?)\s*(?:--\s*(.*))?$")


def suppressions_for(fcompute):
    """{code: justification} declared in the registration's decorator
    region: from the line above the first decorator down to the ``def``."""
    code = getattr(fcompute, "__code__", None)
    if code is None:
        return {}
    try:
        with open(code.co_filename) as f:
            lines = f.readlines()
    except OSError:
        return {}
    start = max(code.co_firstlineno - 2, 0)   # one line above the decorator
    out = {}
    for i in range(start, min(start + 40, len(lines))):
        m = _SUPPRESS_RE.search(lines[i])
        if m:
            why = (m.group(2) or "").strip() or None
            for c in m.group(1).replace(" ", "").split(","):
                if c:
                    out[c] = why
        if lines[i].lstrip().startswith("def ") and i >= code.co_firstlineno:
            break
    return out


# ---------------------------------------------------------------------------
# per-rule checks
# ---------------------------------------------------------------------------

def _check_arity(op, sig):
    n = op.num_inputs
    if n is None:
        if (not sig["has_varargs"] and sig["pos_total"] == sig["pos_required"]
                and op.fargnames is None):
            yield ("GL101", "num_inputs=None (variadic) but fcompute takes "
                   "exactly %d positional arg(s) with no *args and no "
                   "fargnames — the arity cannot actually vary"
                   % sig["pos_total"])
        return
    if n < 0:
        yield ("GL101", "num_inputs=%d is negative" % n)
        return
    if sig["pos_required_only"] > n:
        # required POSITIONAL-ONLY params beyond the input count can never
        # be fed: dispatch passes everything else through the params dict
        # as keywords (Operator.bind), so a required POSITIONAL_OR_KEYWORD
        # tunable (count_sketch's out_dim) is a valid contract
        yield ("GL101", "fcompute requires %d positional-only args but "
               "num_inputs=%d — dispatch can never satisfy the signature"
               % (sig["pos_required_only"], n))
    if not sig["has_varargs"] and n > sig["pos_total"]:
        yield ("GL101", "num_inputs=%d exceeds the fcompute's %d positional "
               "parameter(s) and it takes no *args"
               % (n, sig["pos_total"]))


def _index_bound(op, sig):
    if isinstance(op.num_inputs, int):
        return op.num_inputs
    if sig is not None and not sig["has_varargs"]:
        return sig["pos_total"]
    return None   # true variadic: any index may be valid


def _check_index_field(op, sig, field, code):
    bound = _index_bound(op, sig)
    for idx in getattr(op, field):
        if not isinstance(idx, int) or idx < 0:
            yield (code, "%s contains %r (indices must be non-negative "
                   "ints)" % (field, idx))
        elif bound is not None and idx >= bound:
            yield (code, "%s index %d out of range for arity %d"
                   % (field, idx, bound))


def _check_rng(op, sig):
    has = "rng" in sig["kw_names"] or sig["has_varkw"]
    if op.needs_rng and not has:
        yield ("GL104", "needs_rng=True but fcompute accepts no 'rng' "
               "kwarg — dispatch injects rng= and the call explodes")
    if not op.needs_rng and "rng" in sig["kw_names"]:
        yield ("GL104", "fcompute has an 'rng' parameter but needs_rng is "
               "not declared — the op never receives a key (rng stays at "
               "its default)")


def _check_is_train(op, sig):
    has = "is_train" in sig["kw_names"] or sig["has_varkw"]
    if op.takes_is_train and not has:
        yield ("GL105", "takes_is_train=True but fcompute accepts no "
               "'is_train' kwarg")
    if not op.takes_is_train and "is_train" in sig["kw_names"]:
        yield ("GL105", "fcompute has an 'is_train' parameter but "
               "takes_is_train is not declared — train/eval mode never "
               "reaches the op")


def _check_input_names(op, sig):
    names = op.input_names
    if names is None:
        return
    names = list(names)
    if isinstance(op.num_inputs, int) and len(names) != op.num_inputs:
        yield ("GL106", "input_names lists %d name(s) but num_inputs=%d"
               % (len(names), op.num_inputs))
    if op.num_inputs is None and not sig["has_varargs"]:
        if not (sig["pos_required"] <= len(names) <= sig["pos_total"]):
            yield ("GL106", "input_names lists %d name(s) but the fcompute "
                   "accepts %d..%d positional args"
                   % (len(names), sig["pos_required"], sig["pos_total"]))
    if not sig["has_varargs"] and sig["pos_names"]:
        actual = sig["pos_names"][:len(names)]
        if len(actual) == len(names) and actual != names:
            yield ("GL106", "input_names %r do not match the fcompute's "
                   "positional parameters %r — named binding (arg_names) "
                   "and positional dispatch would disagree"
                   % (names, actual))
    if "bias" in names and "no_bias" not in sig["kw_names"]:
        yield ("GL106", "input_names contains 'bias' but fcompute has no "
               "'no_bias' param — Operator.arg_names' no_bias removal "
               "path can never trigger")


def _check_aux_names(op):
    if not op.aux_input_names:
        return
    if op.input_names is None:
        yield ("GL110", "aux_input_names declared but input_names is None "
               "— aux positions cannot be located")
        return
    missing = [a for a in op.aux_input_names if a not in op.input_names]
    if missing:
        yield ("GL110", "aux_input_names %r missing from input_names"
               % (missing,))


def _check_purity(op, fn_node, sig):
    if fn_node is None:
        return
    n_inputs = (op.num_inputs if isinstance(op.num_inputs, int)
                else sig["pos_required"] if sig else 0)
    input_names = set(sig["pos_names"][:n_inputs]) if sig else set()
    for node in _toplevel_nodes(fn_node):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is None:
            continue
        if len(dotted) == 1 and dotted[0] in _IMPURE_BUILTINS:
            yield ("GL108", "host I/O call %s() at line %d breaks jit "
                   "and eval_shape" % (dotted[0], node.lineno))
            continue
        for pre in _IMPURE_PREFIXES:
            if dotted[:len(pre)] == pre and len(dotted) > len(pre):
                yield ("GL108", "host-side call %s at line %d inside "
                       "fcompute (non-reproducible under jit; breaks the "
                       "no-FInferShape eval_shape design)"
                       % (".".join(dotted), node.lineno))
                break
        else:
            # numpy applied directly to an array INPUT (shape math over
            # static params is fine; materializing a traced input is not)
            if dotted[0] in ("np", "numpy") and any(
                    isinstance(a, ast.Name) and a.id in input_names
                    for a in node.args):
                yield ("GL108", "numpy call %s at line %d consumes array "
                       "input directly — materializes a tracer under jit"
                       % (".".join(dotted), node.lineno))


def _return_arities(fn_node):
    """Known return lengths of the top-level body.  Unknowable returns are
    skipped: calls, bare names (the variable may hold a tuple built
    earlier), conditionals, starred, bare ``return``."""
    known = set()
    for node in _toplevel_nodes(fn_node):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        if isinstance(v, ast.Tuple):
            if any(isinstance(e, ast.Starred) for e in v.elts):
                continue
            known.add(len(v.elts))
        elif isinstance(v, (ast.Call, ast.IfExp, ast.Starred, ast.Name)):
            continue
        else:
            known.add(1)
    return known


def _check_output_arity(op, fn_node):
    if fn_node is None or op.fnum_outputs is not None:
        return
    known = _return_arities(fn_node)
    if len(known) > 1:
        yield ("GL109", "fcompute returns %s outputs depending on params "
               "but registration declares fixed num_outputs=%d and no "
               "fnum_outputs — symbolic executors mis-count outputs"
               % (sorted(known), op.num_outputs))
    elif known and known != {op.num_outputs}:
        yield ("GL109", "fcompute visibly returns %d output(s) but "
               "num_outputs=%d" % (known.pop(), op.num_outputs))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def lint_operator(op):
    """All diagnostics for one Operator (suppressions applied)."""
    sig = _sig_info(op.fcompute)
    fname, line = None, None
    code = getattr(op.fcompute, "__code__", None)
    if code is not None:
        fname, line = code.co_filename, code.co_firstlineno
    findings = []
    if sig is not None:
        for chk in (_check_arity(op, sig),
                    _check_index_field(op, sig, "nograd_inputs", "GL102"),
                    _check_index_field(op, sig, "mutate_inputs", "GL103"),
                    _check_rng(op, sig),
                    _check_is_train(op, sig),
                    _check_input_names(op, sig)):
            findings.extend(chk)
    findings.extend(_check_aux_names(op))
    fn_node = _fcompute_tree(op.fcompute)
    findings.extend(_check_purity(op, fn_node, sig))
    findings.extend(_check_output_arity(op, fn_node))
    sup = suppressions_for(op.fcompute)
    return [Diagnostic(c, op.name, msg, fname, line,
                       suppressed=c in sup, justification=sup.get(c))
            for c, msg in findings]


def _collision_diagnostics(log, names=None):
    for entry in log:
        prev = entry["collided_with"]
        if prev is None:
            continue
        if names is not None and entry["name"] not in names:
            continue
        op = entry["op"]
        msg = ("name %r rebound from Operator(%s) to Operator(%s)%s — the "
               "registry keeps only the last binding, silently"
               % (entry["name"], prev.name, op.name,
                  " (alias of %s)" % entry["alias_of"]
                  if entry["alias_of"] else ""))
        sup = suppressions_for(op.fcompute)
        yield Diagnostic("GL107", entry["name"], msg,
                         entry["file"], entry["line"],
                         suppressed="GL107" in sup,
                         justification=sup.get("GL107"))


def lint_all(names=None):
    """Lint the live registry (+ the registration log for collisions).

    ``names``: optional container of op/alias names to restrict to —
    used by fixture tests to lint only their deliberately-broken ops.
    Importing the ops package is the caller's job (graftlint CLI does it).
    """
    from ..ops.registry import _REGISTRY, registration_log
    diags = []
    seen = set()
    for name in sorted(_REGISTRY):
        if names is not None and name not in names:
            continue
        op = _REGISTRY[name]
        if id(op) in seen:
            continue
        seen.add(id(op))
        diags.extend(lint_operator(op))
    diags.extend(_collision_diagnostics(registration_log(), names))
    return diags
