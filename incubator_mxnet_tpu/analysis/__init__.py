"""Static analysis for the op registry and the bulking engine.

Two cooperating passes (SURVEY §7: ONE registry serves eager, autograd
and symbolic execution — so one malformed registration corrupts all
three at once, and nothing checked the contracts until a user hit them):

* ``contracts`` — the op-contract linter (pass 1): verifies every
  registered Operator against its fcompute signature and AST.  CLI:
  ``python -m incubator_mxnet_tpu.analysis.graftlint``.
* ``engine_check`` — the strict-mode engine verifier (pass 2): hazard
  structures raised by ``engine.py`` when ``GRAFT_ENGINE_CHECK=1``
  (read/write version vectors per view group + the fusion-equivalence
  oracle that replays each flushed segment unfused and bit-compares).

Kept import-light on purpose: ``engine.py`` imports ``engine_check`` at
module load, long before the ops package exists.
"""

__all__ = ["contracts", "engine_check", "graftlint"]
