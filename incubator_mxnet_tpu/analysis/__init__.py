"""Static analysis for the op registry and the bulking engine.

Two cooperating passes (SURVEY §7: ONE registry serves eager, autograd
and symbolic execution — so one malformed registration corrupts all
three at once, and nothing checked the contracts until a user hit them):

* ``contracts`` — the op-contract linter (pass 1): verifies every
  registered Operator against its fcompute signature and AST.  CLI:
  ``python -m incubator_mxnet_tpu.analysis.graftlint``.
* ``engine_check`` — the strict-mode engine verifier (pass 2): hazard
  structures raised by ``engine.py`` when ``GRAFT_ENGINE_CHECK=1``
  (read/write version vectors per view group + the fusion-equivalence
  oracle that replays each flushed segment unfused and bit-compares).
* ``tsan`` — the grafttsan runtime happens-before race detector
  (pass 3, ``GRAFT_TSAN=1``): vector-clock epochs per thread, EH2xx
  reports with both racing stacks for the threaded overlap stack.
* ``lockstep`` — the SPMD lockstep divergence auditor: rolling
  collective-stream hash piggybacked on the dist heartbeat
  (``GRAFT_LOCKSTEP_CHECK``), cross-checked offline by
  ``telemetry/aggregate.py``.
* ``concurrency`` — static GL2xx concurrency lint (pass 4) over the
  package sources, run by the graftlint CLI alongside the op contracts.
* ``compile_safety`` — graftguard (pass 5): GL3xx compile-safety lint
  over trace-eligible closures (host round-trips, traced branching,
  constant-baked hyperparameters, donation hazards) plus the EH3xx
  runtime retrace/donation auditor for the whole-step compiled path
  (``GRAFT_COMPILE_CHECK=1``).

Kept import-light on purpose: ``engine.py`` imports ``engine_check`` at
module load, long before the ops package exists; ``tsan``/``lockstep``
import telemetry lazily (only when a report fires).
"""

__all__ = ["compile_safety", "concurrency", "contracts", "engine_check",
           "graftlint", "lockstep", "tsan"]
