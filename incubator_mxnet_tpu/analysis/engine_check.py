"""Pass 2 — strict-mode engine hazard verification (GRAFT_ENGINE_CHECK=1).

PR 1 made views first-class citizens of bulk segments: a view over a
deferred base records a ``_bulk_view_extract`` program node, write-through
records a ``_bulk_view_write`` that REBINDS the base — so a segment is a
little dataflow program over mutable ownership groups, and the classic
engine hazards (write-after-read against a stale extract, lost-update
double rebinds) exist in miniature.  The production paths guard them with
version counters (NDArray._version / _cache_version), but nothing
*verified* the guards: a bug was caught only if a parity test happened to
cover it ("Memory Safe Computations with XLA Compiler", PAPERS.md, makes
the case for verifying these statically/structurally instead).

This module holds the structured error plus the pure check functions;
``engine.py`` calls them at record and flush time when strict mode is on.
The checks:

=======  ==============================================================
EH101    stale-extract read (write-after-read): an instruction consumes
         a ``_bulk_view_extract`` pending whose base version advanced
         after the extract was recorded — fused replay would ship the
         pre-write value where eager execution reads the post-write one
EH102    double-write rebind (lost update): a ``_bulk_view_write``
         whose base operand is no longer the base's current binding —
         the write would silently discard every rebind in between
EH103    segment-integrity / escaped external: an instruction operand
         that resolves outside the segment's ``ext`` set (out-of-range
         ext slot, forward temp reference) or an ext slot no
         instruction consumes (orphan entries corrupt the replay-cache
         key — see engine.maybe_defer's staging comment)
EH104    fusion divergence: the jitted (fused) segment replay and the
         op-by-op (unfused) replay disagree at the bit level — the
         fusion-equivalence oracle ("Operator Fusion in XLA: Analysis
         and Evaluation" motivates checking fused vs unfused semantics).
         Integer/bool outputs must match exactly; float outputs may
         differ by at most GRAFT_ENGINE_CHECK_ULPS (default 8) units in
         the last place PER RECORDED INSTRUCTION, because XLA fusion
         legitimately re-rounds an elementwise chain by ~1 ULP per op —
         a genuine hazard (stale value, lost update, wrong operand)
         sits millions of ULPs away
=======  ==============================================================
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["EngineHazardError", "check_segment_integrity", "oracle_compare",
           "HAZARDS"]

HAZARDS = {
    "EH101": "stale-extract read (write-after-read hazard)",
    "EH102": "double-write rebind (lost-update hazard)",
    "EH103": "segment integrity violation / escaped external",
    "EH104": "fused/unfused replay divergence (fusion-equivalence oracle)",
}


class EngineHazardError(RuntimeError):
    """Structured engine hazard: ``code`` is one of HAZARDS, ``detail``
    carries the per-hazard specifics for programmatic triage."""

    def __init__(self, code, message, **detail):
        super().__init__("%s [%s]: %s" % (code, HAZARDS.get(code, "?"),
                                          message))
        self.code = code
        self.detail = detail


def check_segment_integrity(instrs, n_ext):
    """EH103: validate every operand reference of a recorded segment.

    ``instrs`` are engine instruction tuples (op_name, params, pkey,
    is_train, in_refs, rng_slot, n_out, rec); ``n_ext`` the ext count.
    """
    produced = 0
    used_ext = set()
    for k, (name, _p, _k, _t, in_refs, rng_slot, n_out, _rec) in \
            enumerate(instrs):
        for tag, i in in_refs:
            if tag == "e":
                if not 0 <= i < n_ext:
                    raise EngineHazardError(
                        "EH103", "instruction #%d (%s) reads ext slot %d "
                        "but the segment holds %d external operand(s) — "
                        "a value escaped the ext set" % (k, name, i, n_ext),
                        instruction=k, op=name, slot=i)
                used_ext.add(i)
            else:
                if not 0 <= i < produced:
                    raise EngineHazardError(
                        "EH103", "instruction #%d (%s) reads temp slot %d "
                        "before it is produced (%d temps exist at that "
                        "point)" % (k, name, i, produced),
                        instruction=k, op=name, slot=i)
        if rng_slot is not None:
            if not 0 <= rng_slot < n_ext:
                raise EngineHazardError(
                    "EH103", "instruction #%d (%s) reads rng ext slot %d "
                    "out of range %d" % (k, name, rng_slot, n_ext),
                    instruction=k, op=name, slot=rng_slot)
            used_ext.add(rng_slot)
        produced += n_out
    orphans = sorted(set(range(n_ext)) - used_ext)
    if orphans:
        raise EngineHazardError(
            "EH103", "ext slot(s) %s are referenced by no instruction — "
            "orphan operands pollute the replay-cache key and pin dead "
            "buffers" % (orphans,), orphans=orphans)


def _ulp_tolerance():
    try:
        return int(os.environ.get("GRAFT_ENGINE_CHECK_ULPS", "8"))
    except ValueError:
        return 8


def _ordered_float_bits(a):
    """Float bit patterns mapped to monotonically increasing UNSIGNED ints
    (the classic total-order transform: negatives are bit-inverted,
    positives get the sign bit set) — works for f16/bf16/f32/f64 since it
    only needs the IEEE sign-magnitude layout.  Staying unsigned avoids
    the int64 wrap a cast would cause for f64 sign-bit patterns."""
    u = np.ascontiguousarray(a).view("u%d" % a.dtype.itemsize)
    sign = np.array(1, dtype=u.dtype) << (8 * a.dtype.itemsize - 1)
    return np.where(u & sign, ~u, u | sign)


def _max_ulp_distance(fa, ua):
    """Max ULP distance between two same-dtype float arrays, or None when
    they differ structurally (NaN pattern mismatch).  ±0 count as 1 ULP
    apart; equal-position NaNs (any payload) count as 0."""
    fnan, unan = np.isnan(fa), np.isnan(ua)
    if not np.array_equal(fnan, unan):
        return None
    of = _ordered_float_bits(fa)
    ou = _ordered_float_bits(ua)
    dist = np.maximum(of, ou) - np.minimum(of, ou)   # exact, unsigned
    dist[fnan.reshape(dist.shape)] = 0
    return int(dist.max()) if dist.size else 0


def oracle_compare(fused, unfused, instrs, live):
    """EH104: compare the jitted segment replay against the op-by-op
    (unfused) replay of the same program over the same operands, at the
    bit level (float outputs get the documented small ULP allowance for
    fusion re-rounding; everything else must match exactly)."""
    tol = _ulp_tolerance() * max(1, len(instrs))
    for pos, (f, u) in enumerate(zip(fused, unfused)):
        fa, ua = np.asarray(f), np.asarray(u)
        if fa.dtype == ua.dtype and fa.shape == ua.shape \
                and fa.tobytes() == ua.tobytes():
            continue
        ulps = None
        is_float = (fa.dtype.kind == "f"
                    or fa.dtype.name.startswith(("bfloat", "float8")))
        if fa.dtype == ua.dtype and fa.shape == ua.shape and is_float \
                and fa.dtype.itemsize in (1, 2, 4, 8):
            ulps = _max_ulp_distance(fa, ua)
            if ulps is not None and ulps <= tol:
                continue
        raise EngineHazardError(
            "EH104", "fused and unfused replay disagree on live output "
            "#%d (shape %s/%s dtype %s/%s, %s) over segment %s"
            % (pos, fa.shape, ua.shape, fa.dtype, ua.dtype,
               "max %s ULPs > tolerance %d" % (ulps, tol)
               if ulps is not None else "structural mismatch",
               [i[0] for i in instrs]),
            output=pos, max_ulps=ulps, tolerance=tol, live=list(live),
            ops=[i[0] for i in instrs])
