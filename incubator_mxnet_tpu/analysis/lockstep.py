"""Lockstep divergence auditor — mechanical enforcement of the SPMD
collective contract.

Every dist path in this codebase keeps one documented invariant (the
"lockstep contract", ``telemetry/blackbox.py``): **the sequence of
collectives each rank issues — order, shape class, payload — is
identical on every rank**, because the tape, the bucket plans and the
env switches are SPMD-identical.  A single rank deviating (a skipped
bucket, a swapped issue order, a rank-local env flip) does not fail
loudly: it silently mispairs XLA collectives and the job hangs or —
worse — computes wrong sums.  Nothing enforced the contract until now.

The auditor folds every collective bracket's identity —
``(seq, path, n_keys, nbytes, keys-digest)`` — into a **rolling hash**
(crc32-combined, kept in int32 range so it rides the existing heartbeat
allreduce verbatim) and keeps a bounded **divergence table** of the
recent per-seq entries.  ``parallel/dist.py`` piggybacks each rank's
``(last_seq, rolling_hash)`` on the worker-heartbeat vector; every rank
then calls :func:`observe` with the full per-rank table, and the FIRST
seq observed with two distinct hashes is reported — rank(s) named,
before a mispaired wire turns into a silent deadlock — via the
flight-recorder ring (``lockstep_divergence``), the
``graft_lockstep_divergence_total`` counter and a log line.  The local
table also lands in every flight-recorder dump (``blackbox.snapshot``),
so the watchdog's hang dump carries the evidence and
``telemetry/aggregate.py::lockstep_check`` can pinpoint the exact
divergent collective offline from N rank dumps.

Host-service paths (``ps_push``/``ps_pull``/``ps_push_async``) are
excluded from the fold: dist_async workers legitimately push at their
own pace — the wire is TCP, not a paired collective.  For those,
:func:`note_order` asserts per-path monotonic issue order instead (the
graftduplex background push client must preserve submission order on
the wire).

Master switch ``GRAFT_LOCKSTEP_CHECK`` (default on — the fold is a
crc32 + deque append per collective).  Like ``GRAFT_BLACKBOX``, set it
IDENTICALLY on every rank: the heartbeat vector's shape depends on it.
"""
from __future__ import annotations

import os
import threading
import zlib
from collections import deque

__all__ = ["enabled", "set_enabled", "fold", "state", "state_lagged",
           "observe", "note_order", "divergence", "table", "snapshot",
           "reset", "keys_digest", "lag", "EXCLUDED_PATHS", "TABLE_SIZE",
           "fold_value", "epoch_base", "rebase", "epoch"]

# host parameter-service RPCs are rank-asymmetric by design (async SGD)
EXCLUDED_PATHS = frozenset(["ps_push", "ps_pull", "ps_push_async"])

TABLE_SIZE = 512                # recent per-seq entries kept for dumps
_SEEN_SEQS = 128                # cross-rank observations retained
_PRIME = 1000003

_enabled_override = None


def set_enabled(flag):
    """Force the auditor on/off (None = defer to GRAFT_LOCKSTEP_CHECK)."""
    global _enabled_override
    _enabled_override = flag


def enabled():
    if _enabled_override is not None:
        return bool(_enabled_override)
    return os.environ.get("GRAFT_LOCKSTEP_CHECK", "1").strip().lower() \
        not in ("0", "false", "no", "off")


_lock = threading.Lock()
_rolling = [0]                  # cumulative int31 hash of the fold stream
_folds = [0]                    # fold-local index: the position of each
#                                 folded collective WITHIN the audited
#                                 stream.  The wire seq can NOT serve
#                                 here: excluded ps_* brackets consume
#                                 the shared blackbox counter at
#                                 rank-dependent timing (dist_async's
#                                 background push client), so raw seqs
#                                 differ across ranks even for identical
#                                 audited streams — hashing them would
#                                 fabricate divergence on healthy jobs
_last_wire_seq = [0]
_table = deque(maxlen=TABLE_SIZE)   # (fold, wire seq, path, n_keys,
#                                     nbytes, digest, rolling-after) —
#                                     the divergence table
_seen = {}                      # seq -> {rank: hash} from heartbeats
_divergence = [None]            # first divergence report (latched)
_order = {}                     # path -> next expected issue index
_order_violations = []
_epoch = [0]                    # membership epoch the stream is based on


def _crc(text):
    return zlib.crc32(text.encode("utf-8", "replace")) & 0x7fffffff


def keys_digest(keys):
    """Deterministic digest of a key list (process-hash-seed-proof)."""
    if not keys:
        return 0
    return _crc(",".join(str(k) for k in keys))


def fold_value(rolling, fold_idx, path, n_keys=None, nbytes=None,
               keys=None):
    """The PURE fold step: combine one collective's identity into a
    rolling int31 at stream position ``fold_idx`` (1-based).  This is
    the exact arithmetic :func:`fold` applies to the module stream —
    exposed so simulated ranks (elastic's single-process membership
    harness) can maintain per-virtual-rank digests that are
    bit-comparable with the real auditor's."""
    digest = _crc("%s|%s|%s|%s" % (path, n_keys, nbytes,
                                   keys_digest(keys)))
    return (int(rolling) * _PRIME + digest + int(fold_idx)) & 0x7fffffff


def epoch_base(epoch):
    """The rolling-hash SEED of membership epoch ``epoch``.  Epoch 0
    (the launch membership) seeds at 0 — the pre-elastic stream is
    unchanged byte-for-byte; later epochs seed on the epoch number so a
    stream that re-based and one that did not can never accidentally
    agree (a rank that missed the re-partition is named immediately,
    not after the next real divergence)."""
    if not epoch:
        return 0
    return _crc("membership-epoch|%d" % int(epoch))


def fold(seq, path, n_keys=None, nbytes=None, keys=None):
    """Fold one collective's identity into the rolling hash (called from
    the blackbox collective bracket at seq-assignment time).  The hash
    mixes the FOLD index, not the wire seq — see ``_folds``.  Returns
    the rolling hash after the fold (None when disabled/excluded)."""
    if not enabled() or path in EXCLUDED_PATHS:
        return None
    digest = _crc("%s|%s|%s|%s" % (path, n_keys, nbytes,
                                   keys_digest(keys)))
    with _lock:
        _folds[0] += 1
        _rolling[0] = (_rolling[0] * _PRIME + digest + _folds[0]) \
            & 0x7fffffff
        _last_wire_seq[0] = int(seq)
        _table.append((_folds[0], int(seq), path, n_keys, nbytes, digest,
                       _rolling[0]))
        return _rolling[0]


def state():
    """(fold_count, rolling_hash) — what the heartbeat ships.  Both are
    fold-local, so two ranks with identical audited streams match even
    when rank-asymmetric ps_* brackets skewed their wire seqs."""
    with _lock:
        return _folds[0], _rolling[0]


def lag():
    """GRAFT_LOCKSTEP_LAG (default 8): how many folds behind the head
    the lagged-prefix sample trails."""
    try:
        n = int(os.environ.get("GRAFT_LOCKSTEP_LAG", "8"))
    except ValueError:
        return 8
    return max(n, 1)


def state_lagged():
    """(fold_count, rolling_hash, lag_fold, lag_hash) — the head pair
    PLUS the rolling hash as it stood ``lag()`` folds ago (read from the
    divergence table).  ONLINE BISECTION (PR 10 carry-forward): with two
    prefix points per heartbeat accumulating in every peer's ``_seen``
    table, :func:`observe` can bracket a divergence between the last
    MATCHING fold and the first MISMATCHING one — when they are
    adjacent, the exact divergent collective is pinned online, not only
    in offline ``--analyze``.  ``(0, 0)`` lag halves ship while the
    stream is shorter than the lag (peers skip zero folds)."""
    with _lock:
        head_fold, head_hash = _folds[0], _rolling[0]
        want = head_fold - lag()
        lag_fold, lag_hash = 0, 0
        if want > 0:
            for fi, _s, _p, _nk, _nb, _d, r in reversed(_table):
                if fi == want:
                    lag_fold, lag_hash = want, r
                    break
                if fi < want:
                    break       # evicted from the bounded table: ship
                    #             nothing rather than a fabricated hash
        return head_fold, head_hash, lag_fold, lag_hash


def divergence():
    """The first detected divergence record, or None."""
    return _divergence[0]


def table(last=None):
    """The recent divergence-table entries as dicts (oldest first).
    ``fold`` is the audited-stream position (the online matching key);
    ``seq`` the wire seq (the offline ``--analyze`` matching key)."""
    with _lock:
        rows = list(_table)
    if last is not None:
        rows = rows[-last:]
    return [{"fold": fi, "seq": s, "path": p, "n_keys": nk, "nbytes": nb,
             "digest": d, "rolling": r}
            for fi, s, p, nk, nb, d, r in rows]


def observe(rank_table, my_rank=None):
    """Cross-check one heartbeat's per-rank ``{rank: (fold_count, hash)}``
    or ``{rank: (fold_count, hash, lag_fold, lag_hash)}`` (the
    lagged-prefix pair :func:`state_lagged` ships).

    Two detectors, both keyed on the rank-comparable FOLD index:

    * **exact-position match** — two ranks reporting different rolling
      hashes for the SAME fold count diverged at or before it (the
      hash is cumulative); positions accumulate across heartbeats in
      ``_seen`` since ranks advance at different moments;
    * **self-table lookback** — a peer's ``(fold, hash)`` is compared
      against the LOCAL divergence table's rolling hash at that same
      fold.  This is what catches a *skipped* collective: the skipping
      rank's fold counts misalign with everyone else's forever after
      (an exact-position match may never recur), but its hash at fold F
      must equal our recorded rolling at fold F — a mere laggard
      matches, a diverged stream does not.

    ONLINE BISECTION: the lagged-prefix points double the sampled
    prefix density, and the report brackets the divergence between the
    peer's last MATCHING fold and first MISMATCHING one.  When the two
    are adjacent the report is ``pinned`` and carries the local table's
    ``divergent_collective`` row (path, keys digest, nbytes) — the
    exact collective, named online.

    The first divergence is reported once: a ``lockstep_divergence``
    flight-recorder event carrying the per-rank hashes, the local
    recent table, and the rank(s) disagreeing with the local stream.
    Returns the report dict (or None)."""
    if not enabled():
        return None
    report = None
    with _lock:
        for rank, entry in rank_table.items():
            points = [(int(entry[0]), int(entry[1]))]
            if len(entry) >= 4:
                points.append((int(entry[2]), int(entry[3])))
            for fold, h in points:
                if fold <= 0:
                    continue
                _seen.setdefault(fold, {})[int(rank)] = h
        while len(_seen) > _SEEN_SEQS:
            del _seen[min(_seen)]
        if _divergence[0] is None:
            report = _first_divergence_locked(my_rank)
            if report is not None:
                _divergence[0] = report
    if report is not None:
        _emit(report)
    return report


def _pin_locked(local_at, rank, first_bad):
    """Bisect one peer's divergence against the local stream: the last
    fold (< first_bad) where the peer's sampled hash MATCHES the local
    rolling brackets the divergence from below.  Adjacent bounds pin the
    exact collective — the local table row at ``first_bad`` IS the first
    collective the streams disagree on.  Returns (last_match_fold|None,
    pinned, collective-row|None)."""
    last_match = None
    for fold, ranks in _seen.items():
        h = ranks.get(int(rank))
        if h is None or fold >= first_bad:
            continue
        if local_at.get(fold) == h:
            last_match = fold if last_match is None \
                else max(last_match, fold)
    pinned = last_match is not None and last_match == first_bad - 1
    row = None
    if pinned:
        for fi, s, p, nk, nb, d, r in _table:
            if fi == first_bad:
                row = {"fold": fi, "seq": s, "path": p, "n_keys": nk,
                       "nbytes": nb, "digest": d}
                break
        pinned = row is not None
    return last_match, pinned, row


def _first_divergence_locked(my_rank):
    """The earliest observed divergence, or None (call under _lock)."""
    # self-table lookback: a peer's hash vs the local rolling at the
    # same fold position
    local_at = {fi: r for fi, _s, _p, _nk, _nb, _d, r in _table}
    for fold in sorted(_seen):
        for rank, h in sorted(_seen[fold].items()):
            if my_rank is not None and int(rank) == int(my_rank):
                continue
            mine = local_at.get(fold)
            if mine is not None and mine != h:
                last_match, pinned, row = _pin_locked(local_at, rank,
                                                      fold)
                report = {
                    "first_divergent_fold": fold,
                    "last_matching_fold": last_match,
                    "pinned": pinned,
                    "rank_hashes": {str(rank): h, str(my_rank): mine},
                    "divergent_ranks": [int(rank)],
                    "observer_rank": my_rank,
                }
                if row is not None:
                    report["divergent_collective"] = row
                return report
    # exact-position cross-peer match (covers folds our table evicted)
    for fold in sorted(_seen):
        ranks = _seen[fold]
        if len(set(ranks.values())) > 1:
            my_hash = None
            if my_rank is not None:
                my_hash = ranks.get(int(my_rank))
            if my_hash is None:
                # fall back: majority hash plays "reference"
                counts = {}
                for v in ranks.values():
                    counts[v] = counts.get(v, 0) + 1
                my_hash = max(counts, key=counts.get)
            return {
                "first_divergent_fold": fold,
                "last_matching_fold": None,
                "pinned": False,
                "rank_hashes": {str(r): v
                                for r, v in sorted(ranks.items())},
                "divergent_ranks": sorted(r for r, v in ranks.items()
                                          if v != my_hash),
                "observer_rank": my_rank,
            }
    return None


def _emit(report):
    try:
        from ..telemetry import blackbox as _blackbox
        _blackbox.record("lockstep_divergence",
                         table=table(last=32), **report)
    except Exception:
        pass
    try:
        from ..telemetry import metrics as _metrics
        _metrics.lockstep_divergence()
    except Exception:
        pass
    import logging
    if report.get("pinned"):
        c = report["divergent_collective"]
        logging.getLogger("graftlockstep").error(
            "LOCKSTEP DIVERGENCE: rank(s) %s issued a different "
            "collective stream — PINNED to fold %d: %s (wire seq %s, "
            "n_keys %s, nbytes %s, keys digest %s); per-rank rolling "
            "hashes %s.",
            report["divergent_ranks"], report["first_divergent_fold"],
            c["path"], c["seq"], c["n_keys"], c["nbytes"], c["digest"],
            report["rank_hashes"])
        return
    logging.getLogger("graftlockstep").error(
        "LOCKSTEP DIVERGENCE: rank(s) %s issued a different collective "
        "stream — first divergent stream position (fold) <= %d (last "
        "matching fold %s; per-rank rolling hashes %s). The wire will "
        "mispair; dump the flight recorders and run `telemetry "
        "--analyze` on them to name the exact collective.",
        report["divergent_ranks"], report["first_divergent_fold"],
        report.get("last_matching_fold"), report["rank_hashes"])


def note_order(path, issue_idx):
    """Assert per-path monotonic issue order for host-service wires (the
    graftduplex background push client): ``issue_idx`` values must
    arrive 0, 1, 2, ...  A violation is recorded once per path."""
    if not enabled():
        return True
    with _lock:
        expected = _order.get(path, 0)
        _order[path] = max(expected, issue_idx + 1)
        ok = issue_idx == expected
        if not ok:
            if any(v["path"] == path for v in _order_violations):
                return False
            violation = {"path": path, "expected": expected,
                         "got": issue_idx}
            _order_violations.append(violation)
    if not ok:
        try:
            from ..telemetry import blackbox as _blackbox
            _blackbox.record("lockstep_order_violation", **violation)
        except Exception:
            pass
        import logging
        logging.getLogger("graftlockstep").error(
            "issue-order violation on %r: executed index %d, expected %d "
            "— the background client reordered the wire", path,
            issue_idx, expected)
    return ok


def epoch():
    """The membership epoch the current fold stream is based on."""
    return _epoch[0]


def rebase(new_epoch):
    """Re-base the fold stream at a membership-epoch boundary
    (graftelastic).  Every surviving rank calls this at the SAME stream
    position (behind the repartition step barrier), so the divergence
    contract holds ACROSS epochs: pre-epoch history — the divergence
    table, the cross-rank ``_seen`` observations, the fold counter —
    is dropped (a departed rank's stale hashes must not be compared
    against the re-based stream, and survivors' fold counts restart
    together), and the rolling hash re-seeds on :func:`epoch_base` so
    epoch N and epoch M streams can never accidentally match.  A
    latched divergence report is KEPT — it is evidence, not state.
    Per-path issue-order counters also restart: the duplex background
    wire drains before a re-partition (``DistKVStore.quiesce``), so
    post-epoch issue indices legitimately begin at 0 again."""
    with _lock:
        _epoch[0] = int(new_epoch)
        _rolling[0] = epoch_base(new_epoch)
        _folds[0] = 0
        _last_wire_seq[0] = 0
        _table.clear()
        _seen.clear()
        _order.clear()
    return _rolling[0]


def snapshot():
    """Dump-embeddable auditor state (blackbox.snapshot folds this into
    every flight-recorder dump, so a watchdog hang dump carries the
    divergence table)."""
    folds, rolling = state()
    return {"enabled": enabled(), "folds": folds,
            "last_wire_seq": _last_wire_seq[0],
            "rolling_hash": rolling, "divergence": _divergence[0],
            "epoch": _epoch[0],
            "order_violations": list(_order_violations),
            "table": table(last=64)}


def reset():
    """Drop all auditor state (tests / between training jobs)."""
    with _lock:
        _rolling[0] = 0
        _folds[0] = 0
        _last_wire_seq[0] = 0
        _table.clear()
        _seen.clear()
        _divergence[0] = None
        _order.clear()
        del _order_violations[:]
        _epoch[0] = 0
